"""Fused Pallas flash-decode kernel: one new token vs a KV cache.

Inference surface the reference never had (it is a forward-only batch
kernel, `attention-mpi.c:191-407`); this is the autoregressive-decoding
analog of its online-softmax pass (`attention-mpi.c:168-189`): a single
query row scans the cached KV rows with a running (max, sumexp)
recurrence, fused in one kernel (the tile body is shared with the
forward kernel, `flash.py::_flash_tile`).

TPU-native design notes:
  * Decode is HBM-bandwidth-bound (the used KV prefix streams through
    VMEM once per step), so the kernel's job is to keep the DMA pipeline
    full — the KV grid dimension gives Pallas' automatic double
    buffering — and to spend nothing on the unused cache tail: the
    per-sequence lengths are **scalar-prefetched** so the K/V BlockSpec
    index maps clamp every out-of-range block index to the last valid
    block.  Pallas elides the DMA when consecutive grid steps map to the
    same block, and `@pl.when(j * block_k < valid)` skips the compute,
    so both bandwidth and FLOPs scale with the *used* prefix, not the
    cache capacity — at ``block_k`` granularity: the default 2048 rows
    (sweep-chosen: 512-row blocks cap streaming at ~450-500 GB/s where
    2048 reaches ~730-900) means a short prefix still pays one full
    block per KV head (~0.05 ms); pass a smaller ``block_k`` if a
    workload lives entirely at short lengths.
  * All Q heads sharing one KV head (GQA) are processed together as the
    row-block of a single (group, block_k) MXU matmul, so the KV cache
    is read once per KV head, not once per Q head.
  * Per-batch cache lengths make a ragged batch decode in one call with
    no host-side bucketing.

Layout: Q (B, H, d) — one token per sequence; caches (B, Hkv, N, d|dv)
with static capacity N; lengths (B,) int32 (or a scalar, broadcast).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu import obs
from attention_tpu.ops.flash import (
    _LOG2E,
    _STAT_LANES,
    NEG_INF,
    _ceil_to,
    _compiler_params,
    _flash_tile,
    _should_interpret,
    _tuned_max_mode,
    check_softcap,
)

# Op-dispatch telemetry (attention_tpu.obs, off by default): one tick
# per host-side dispatch; calls inside an enclosing jit tick per trace.
# `ops.decode.lowered` ticks at TRACE time inside the jitted bodies and
# records which rescaling-math variant each dispatch actually lowered
# (the decode analog of `ops.flash.lowered`).
_DECODE_CALLS = obs.counter(
    "ops.decode.calls", "flash_decode dispatches by cache shape bucket")
_DECODE_LOWERED = obs.counter(
    "ops.decode.lowered",
    "decode kernel lowerings by requested/resolved max mode")

#: max_mode values the decode kernels accept — "bound" is forward-only
#: (it needs the key-norm prefetch the decode grid does not carry).
DECODE_MAX_MODES = ("online", "flashd", "amla", "auto")


def _resolve_decode_max_mode(max_mode: str, *, batch, h, hkv, n, d,
                             dtype, window, sinks) -> str:
    """Validate and statically resolve a decode-side ``max_mode``:
    "auto" consults the tuning tables (decode family key), anything the
    table cannot legally pick falls back to the online oracle."""
    if max_mode not in DECODE_MAX_MODES:
        raise ValueError(
            f"unknown decode max_mode {max_mode!r}; one of "
            f"{DECODE_MAX_MODES} (bound mode is forward-only)")
    if max_mode != "auto":
        return max_mode
    return _tuned_max_mode(
        "decode", dtype=dtype, allowed=("online", "flashd", "amla"),
        heads=h, kv_heads=hkv, seq=n, dim=d, batch=batch,
        window=window, sinks=sinks)


def _decode_kernel(
    lens_ref, q_ref, k_ref, v_ref, o_ref, acc_scr, m_scr, l_scr,
    *, hkv: int, block_k: int, block_q: int, n: int,
    softcap2: float | None = None, window: int | None = None,
    sinks: int | None = None, chunk: int | None = None,
    variant: str = "online",
):
    """One (batch*kv-head, kv-block) grid step of cached decode.

    ``window`` restricts attention to the last ``window`` cached rows of
    each sequence (the query sits at position valid-1), with the first
    ``sinks`` rows pinned (StreamingLLM) — the decode-side counterpart
    of the forward kernel's banded mask.

    ``chunk`` (static): speculative-verify mode — the q block packs
    ``chunk`` consecutive query tokens per group head ((g, s) rows,
    s-minor), the per-sequence length is the length AFTER the chunk's
    rows were appended, and row (g, s) sits at position
    ``valid - chunk + s``: causal within the chunk, window/sinks bands
    per row.  One cache stream scores the whole chunk — the
    arithmetic-intensity win speculative decoding exists for.
    """
    bh = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)
    valid = lens_ref[bh // hkv]
    if chunk is not None:
        # per-row bands ride the causal+window mask in _flash_tile; the
        # block-level live/clamp below widens the window by chunk-1 so
        # every row's band is covered
        w_eff = None if window is None else window + chunk - 1
    else:
        w_eff = window
    kv_min = None
    if chunk is None and window is not None:
        kv_min = jnp.maximum(valid - window, 0)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = banded_live(j, valid, block_k, w_eff, sinks)

    @pl.when(live)
    def _tile():
        if chunk is None:
            _flash_tile(
                q_ref, k_ref, v_ref, acc_scr, m_scr, l_scr,
                valid=valid, q_offset=0, kv_offset=0,
                kv_idx=j, q_idx=0,
                n_true=n, block_k=block_k, causal=False, block_q=block_q,
                softcap2=softcap2, kv_min=kv_min, sinks=sinks,
                variant=variant,
            )
        else:
            _flash_tile(
                q_ref, k_ref, v_ref, acc_scr, m_scr, l_scr,
                valid=valid, q_offset=valid - chunk, kv_offset=0,
                kv_idx=j, q_idx=0,
                n_true=n, block_k=block_k, causal=True, block_q=block_q,
                softcap2=softcap2, window=window, sinks=sinks,
                pos_mod=chunk, variant=variant,
            )

    @pl.when(j == num_j - 1)
    def _finalize():
        if variant == "flashd":
            # the accumulator is already normalized — no epilogue divide
            o_ref[0] = acc_scr[...].astype(o_ref.dtype)
        else:
            l = jnp.max(l_scr[...], axis=-1, keepdims=True)
            # empty-cache guard, the reference's 1/gsum div-by-zero
            # guard (attention-mpi.c:358-362)
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def check_band(window, sinks) -> None:
    """Shared validation for the decode-side window/sinks contract
    (mirrors flash_attention's): sinks require a window, both >= 1."""
    if sinks is not None:
        if window is None:
            raise ValueError("sinks require window= (see flash_attention)")
        if sinks < 1:
            raise ValueError(f"sinks must be >= 1, got {sinks}")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def banded_live(j, valid, block_k: int, window, sinks):
    """Compute-guard predicate paired with :func:`banded_block_clamp`:
    True for blocks holding valid rows inside the window band or pinned
    sink rows.  The two MUST stay mirrored — a block the clamp remaps
    must never compute, and a live block must keep its identity index."""
    live = j * block_k < valid
    if window is not None:
        above_min = (j + 1) * block_k > jnp.maximum(valid - window, 0)
        if sinks:
            above_min = jnp.logical_or(above_min, j * block_k < sinks)
        live = jnp.logical_and(live, above_min)
    return live


def banded_block_clamp(j, valid, block_k: int, window, sinks):
    """DMA-eliding clamp for a decode kernel's KV block index.

    Past-the-prefix blocks clamp to the last valid block (Pallas elides
    the HBM->VMEM DMA when consecutive grid steps map to the same
    block, so bandwidth scales with the used prefix).  With a window,
    leading blocks below the window start clamp UP to the window's
    first block — keeping sink blocks at their identity indices when
    sinks are on — so bandwidth scales with the WINDOW, not the prefix.
    Shared by the bf16 (`flash_decode`) and int8
    (`flash_decode_quantized`) kernels; the clamp must mirror their
    `live` compute guards.
    """
    last = jnp.maximum((valid + block_k - 1) // block_k - 1, 0)
    jj = jnp.minimum(j, last)
    if window is not None:
        floor = jnp.minimum(jnp.maximum(valid - window, 0) // block_k, last)
        if sinks:
            sink_last = (sinks - 1) // block_k
            jj = jnp.where(jj <= sink_last, jj, jnp.maximum(jj, floor))
        else:
            jj = jnp.maximum(jj, floor)
    return jj


def _pick_block_k(n: int, want: int) -> int:
    """Largest multiple of 128 that divides n and is <= want."""
    if n % 128:
        raise ValueError(f"cache capacity {n} must be a multiple of 128")
    bk = min(_ceil_to(want, 128), n)
    while n % bk:
        bk -= 128
    return bk


# The sweep-chosen dense-decode KV block (the heuristic the tuner falls
# back to): 512-row blocks cap streaming at ~450-500 GB/s where 2048
# reaches ~730-900 (module docstring).
_DEFAULT_BLOCK_K = 2048


def _default_block_k(batch: int, h: int, hkv: int, n: int, d: int,
                     dtype, window, sinks) -> int:
    """Resolve an unspecified decode ``block_k``: tuning tables first
    (user cache -> shipped table, keyed by device kind — see
    `attention_tpu.tuning`), then the measured `_DEFAULT_BLOCK_K`, so
    hosts with no cache entries behave exactly as before."""
    try:
        from attention_tpu.tuning.lookup import key_fields, lookup

        entry = lookup(
            "decode", dtype=dtype,
            **key_fields("decode", heads=h, kv_heads=hkv, seq=n, dim=d,
                         batch=batch, window=window, sinks=sinks),
        )
        if entry is not None:
            bk = int(entry["block_k"])
            if bk > 0 and bk % 128 == 0:
                return bk
    except Exception:  # noqa: BLE001 - tuning must never break dispatch
        pass
    return _DEFAULT_BLOCK_K


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_k", "interpret", "softcap", "window",
                     "sinks", "max_mode"),
)
def _flash_decode_jit(
    q: jax.Array,        # (B, H, d)
    k_cache: jax.Array,  # (B, Hkv, N, d)
    v_cache: jax.Array,  # (B, Hkv, N, dv)
    lengths: jax.Array,  # (B,) int32 valid rows per sequence, or scalar
    *,
    scale: float | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    max_mode: str = "online",
) -> jax.Array:
    """softmax(q K[:len]^T * scale) V[:len] per sequence -> (B, H, dv).

    ``softcap`` applies Gemma-2-style logit capping before softmax.
    ``window`` attends only the last ``window`` valid rows per sequence
    (sliding-window serving on a dense/ragged cache — each query sits at
    its sequence's position ``len-1``); ``sinks`` additionally pins the
    first ``sinks`` rows (StreamingLLM), requires ``window``.
    ``max_mode`` picks the rescaling math ("online"/"flashd"/"amla",
    same softmax — see `flash_attention`); "auto" consults the tuning
    tables and falls back to "online"."""
    check_softcap(softcap)
    check_band(window, sinks)
    if q.ndim != 3 or k_cache.ndim != 4 or v_cache.ndim != 4:
        raise ValueError(
            f"expected q (B,H,d), caches (B,Hkv,N,d): got "
            f"Q{q.shape} K{k_cache.shape} V{v_cache.shape}"
        )
    b, h, d = q.shape
    bk_, hkv, n, dk = k_cache.shape
    dv = v_cache.shape[-1]
    if bk_ != b or v_cache.shape[:3] != (b, hkv, n) or dk != d:
        raise ValueError(
            f"cache shapes inconsistent: Q{q.shape} K{k_cache.shape} "
            f"V{v_cache.shape}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))

    # Pre-scale Q by scale*log2(e) (flash.py's log2-domain trick) and lay
    # the q-head group out as the row block of one matmul per KV head.
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qs = qs.reshape(b * hkv, group, d)
    group_pad = _ceil_to(group, 16)  # min sublane tile (bf16-safe)
    if group_pad != group:
        qs = jnp.pad(qs, ((0, 0), (0, group_pad - group), (0, 0)))

    if block_k is None:
        block_k = _default_block_k(b, h, hkv, n, d, q.dtype, window, sinks)
    block_k = _pick_block_k(n, block_k)
    variant = _resolve_decode_max_mode(
        max_mode, batch=b, h=h, hkv=hkv, n=n, d=d, dtype=q.dtype,
        window=window, sinks=sinks)
    if obs.is_enabled():
        _DECODE_LOWERED.inc(requested=max_mode, lowered=variant,
                            entry="decode")
    kc = k_cache.reshape(b * hkv, n, d)
    vc = v_cache.reshape(b * hkv, n, dv)

    def kv_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, banded_block_clamp(j, valid, block_k, window, sinks), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n // block_k),
        in_specs=[
            pl.BlockSpec((1, group_pad, d), lambda bh, j, lens_ref: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, dv), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, group_pad, dv), lambda bh, j, lens_ref: (bh, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group_pad, dv), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, hkv=hkv, block_k=block_k, block_q=group_pad,
            n=n,
            softcap2=None if softcap is None else softcap * _LOG2E,
            window=window, sinks=sinks, variant=variant,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, group_pad, dv), v_cache.dtype),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * h * n * (d + dv),
            bytes_accessed=(kc.size + vc.size) * kc.dtype.itemsize
            + qs.size * qs.dtype.itemsize,
            transcendentals=b * h * n,
        ),
        interpret=interpret,
    )(lens, qs, kc, vc)

    return out[:, :group].reshape(b, h, dv)


def flash_decode(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                 lengths: jax.Array, **kwargs) -> jax.Array:
    """One-token-per-sequence decode (telemetry shim; full docs on
    :func:`_flash_decode_jit`)."""
    if obs.is_enabled():
        _DECODE_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[0], k_cache.shape[-2],
                                    q.shape[-1]),
            entry="decode")
    return _flash_decode_jit(q, k_cache, v_cache, lengths, **kwargs)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_k", "interpret", "softcap", "window",
                     "sinks", "max_mode"),
)
def _flash_decode_chunk_jit(
    q: jax.Array,          # (B, H, S, d) — S new tokens per sequence
    k_cache: jax.Array,    # (B, Hkv, N, d), chunk rows ALREADY appended
    v_cache: jax.Array,    # (B, Hkv, N, dv)
    new_lengths: jax.Array,  # (B,) int32 lengths AFTER the append
    *,
    scale: float | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    max_mode: str = "online",
) -> jax.Array:
    """Score S appended tokens per sequence in ONE cache stream
    -> (B, H, S, dv).

    The speculative-verify primitive on ragged caches: token s of
    sequence b sits at position ``new_lengths[b] - S + s`` and attends
    its causal prefix (window/sinks bands per row).  Equivalent to S
    sequential `flash_decode` calls but reads the cache once — the
    chunked-prefill arithmetic-intensity trade (the reference's Q-batch
    pipelining idea, `attention-mpi.c:268-330`, turned inward), with the
    whole (group, S) row block as one MXU matmul per KV block (the GQA
    trick of this module extended to chunk rows)."""
    check_softcap(softcap)
    check_band(window, sinks)
    if q.ndim != 4 or k_cache.ndim != 4 or v_cache.ndim != 4:
        raise ValueError(
            f"expected q (B,H,S,d), caches (B,Hkv,N,d): got "
            f"Q{q.shape} K{k_cache.shape} V{v_cache.shape}"
        )
    b, h, s_chunk, d = q.shape
    bk_, hkv, n, dk = k_cache.shape
    dv = v_cache.shape[-1]
    if bk_ != b or v_cache.shape[:3] != (b, hkv, n) or dk != d:
        raise ValueError(
            f"cache shapes inconsistent: Q{q.shape} K{k_cache.shape} "
            f"V{v_cache.shape}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens = jnp.broadcast_to(jnp.asarray(new_lengths, jnp.int32), (b,))

    # rows pack the whole GQA group's chunk: (g, s) with s minor, so the
    # kernel's pos_mod=s_chunk recovers each row's token index
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qs = qs.reshape(b, hkv, group * s_chunk, d).reshape(
        b * hkv, group * s_chunk, d)
    rows = group * s_chunk
    rows_pad = _ceil_to(rows, 16)  # min sublane tile (bf16-safe)
    if rows_pad != rows:
        qs = jnp.pad(qs, ((0, 0), (0, rows_pad - rows), (0, 0)))

    if block_k is None:
        block_k = _default_block_k(b, h, hkv, n, d, q.dtype, window, sinks)
    block_k = _pick_block_k(n, block_k)
    variant = _resolve_decode_max_mode(
        max_mode, batch=b, h=h, hkv=hkv, n=n, d=d, dtype=q.dtype,
        window=window, sinks=sinks)
    if obs.is_enabled():
        _DECODE_LOWERED.inc(requested=max_mode, lowered=variant,
                            entry="chunk")
    kc = k_cache.reshape(b * hkv, n, d)
    vc = v_cache.reshape(b * hkv, n, dv)
    w_eff = None if window is None else window + s_chunk - 1

    def kv_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, banded_block_clamp(j, valid, block_k, w_eff, sinks), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n // block_k),
        in_specs=[
            pl.BlockSpec((1, rows_pad, d), lambda bh, j, lens_ref: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, dv), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, rows_pad, dv), lambda bh, j, lens_ref: (bh, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((rows_pad, dv), jnp.float32),
            pltpu.VMEM((rows_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((rows_pad, _STAT_LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, hkv=hkv, block_k=block_k, block_q=rows_pad,
            n=n,
            softcap2=None if softcap is None else softcap * _LOG2E,
            window=window, sinks=sinks, chunk=s_chunk, variant=variant,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, rows_pad, dv),
                                       v_cache.dtype),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * h * s_chunk * n * (d + dv),
            bytes_accessed=(kc.size + vc.size) * kc.dtype.itemsize
            + qs.size * qs.dtype.itemsize,
            transcendentals=b * h * s_chunk * n,
        ),
        interpret=interpret,
    )(lens, qs, kc, vc)

    return out[:, :rows].reshape(b, hkv, group, s_chunk, dv).reshape(
        b, h, s_chunk, dv)


def flash_decode_chunk(q: jax.Array, k_cache: jax.Array,
                       v_cache: jax.Array, new_lengths: jax.Array,
                       **kwargs) -> jax.Array:
    """Chunked (speculative-verify) decode (telemetry shim; full docs
    on :func:`_flash_decode_chunk_jit`)."""
    if obs.is_enabled():
        _DECODE_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[0], k_cache.shape[-2],
                                    q.shape[-1]),
            entry="chunk")
    return _flash_decode_chunk_jit(q, k_cache, v_cache, new_lengths,
                                   **kwargs)
