"""XLA reference attention: the un-fused, compiler-scheduled implementation.

This is the JAX analog of the reference's serial path (`attention.c:20-75`)
— plain QK^T → softmax → V with no manual tiling — but expressed so XLA can
fuse and tile it for the MXU.  It serves three roles:

  1. a second correctness reference (vs the fp64 NumPy oracle) that runs
     on-device;
  2. the differentiable fallback used in training when a custom-VJP flash
     path is not wanted;
  3. the baseline the Pallas flash kernel's speedup is measured against
     (the "MPI baseline" role in the reference's ablation tables,
     README.md:95-102).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from attention_tpu.ops.flash import check_softcap


@functools.partial(jax.jit,
                   static_argnames=("scale", "precision", "softcap"))
def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    precision: str | None = None,
    softcap: float | None = None,
) -> jax.Array:
    """softmax(q k^T * scale) v over the last two axes.

    Shapes: q (..., m, dk), k (..., n, dk), v (..., n, dv).  Leading axes
    broadcast (batch/heads).  Scores and softmax run in float32 regardless
    of input dtype — the mixed-precision boundary the reference implements
    with its d2f/f2d converters (`attention-mpi.c:31-101`): narrow compute
    inside, wider type at the edges.
    """
    check_softcap(softcap)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum(
        "...md,...nd->...mn", q, k, precision=precision,
        preferred_element_type=jnp.float32,
    ) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "...mn,...nd->...md", weights.astype(v.dtype), v, precision=precision,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)


def attention_xla_partials(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    kv_valid=None,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    softcap: float | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Unnormalized attention partials over a local KV shard.

    Returns ``(out_unnorm, row_max, row_sumexp)`` — the same per-shard
    contract as the reference's local flash pass, which leaves each rank
    holding (contrib, lmax, lsum) before the global two-phase normalization
    (`attention-mpi.c:168-189`).  Used by the distributed paths when the
    Pallas kernel is unavailable; all stats in float32.

    ``kv_valid`` (optional dynamic scalar) masks trailing padded KV rows;
    ``causal`` with ``q_offset``/``kv_offset`` applies the global causal
    triangle over shards — both mirror the flash kernel's masking.
    """
    check_softcap(softcap)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    grouped = (
        q.ndim >= 3 and k.ndim >= 3 and q.shape[-3] != k.shape[-3]
    )
    if grouped:
        # GQA: fold Q heads into (kv_heads, group) and contract against the
        # unexpanded K/V — no repeated-KV materialization (the flash kernel
        # achieves the same via its head-group BlockSpec index map)
        hq, hkv = q.shape[-3], k.shape[-3]
        if hq % hkv != 0:
            raise ValueError(
                f"q heads {hq} not a multiple of kv heads {hkv}"
            )
        group = hq // hkv
        qg = q.reshape(*q.shape[:-3], hkv, group, *q.shape[-2:])
        scores = jnp.einsum(
            "...hgmd,...hnd->...hgmn", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale
        scores = scores.reshape(*scores.shape[:-4], hq, *scores.shape[-2:])
    else:
        scores = jnp.einsum(
            "...md,...nd->...mn", q, k, preferred_element_type=jnp.float32
        ) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    masked = False
    if kv_valid is not None:
        col = jnp.arange(k.shape[-2])
        scores = jnp.where(col < kv_valid, scores, -jnp.inf)
        masked = True
    if causal:
        col = jnp.arange(k.shape[-2]) + kv_offset
        row = jnp.arange(q.shape[-2]) + q_offset
        scores = jnp.where(col[None, :] <= row[:, None], scores, -jnp.inf)
        masked = True
    row_max = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - row_max[..., None])
    if masked:
        p = jnp.where(jnp.isneginf(row_max)[..., None], 0.0, p)
    row_sum = jnp.sum(p, axis=-1)
    if grouped:
        pg = p.reshape(*p.shape[:-3], hkv, group, *p.shape[-2:])
        out_unnorm = jnp.einsum(
            "...hgmn,...hnd->...hgmd", pg.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        out_unnorm = out_unnorm.reshape(
            *out_unnorm.shape[:-4], hq, *out_unnorm.shape[-2:]
        )
    else:
        out_unnorm = jnp.einsum(
            "...mn,...nd->...md", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
    return out_unnorm.astype(jnp.float32), row_max, row_sum


def ragged_paged_reference(
    q,
    k_pool,
    v_pool,
    page_table,
    kv_lens,
    cu_q_lens,
    distribution,
    *,
    scale: float | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
):
    """fp64 NumPy oracle for `ops.ragged_paged.ragged_paged_attention`.

    Same packed contract: ``q`` (1, Hq, T, d) with per-request spans
    delimited by ``cu_q_lens`` (S+1,), per-slot POST-append ``kv_lens``
    (S,) read through ``page_table`` (S, max_pages) rows of the
    (P, Hkv, page, d) pools, ``distribution`` (2,) = (num_decode,
    num_active).  A span's token at offset ``s`` attends cache
    positions ``<= kv_len - q_len + s`` (optionally banded to the last
    ``window`` positions plus ``sinks`` leading ones).  Pad tokens
    return zeros; a poisoned slot (kv_len < 0) returns NaN rows.
    Everything runs in float64 off-device — the ground truth the chaos
    fuzzer and the tier-1 kernel tests scan against.
    """
    import numpy as np

    check_softcap(softcap)
    q = np.asarray(q, np.float64)
    k_pool = np.asarray(k_pool, np.float64)
    v_pool = np.asarray(v_pool, np.float64)
    page_table = np.asarray(page_table)
    kv_lens = np.asarray(kv_lens)
    cu_q_lens = np.asarray(cu_q_lens)
    num_active = int(np.asarray(distribution)[1])
    _, hq, t_pad, d = q.shape
    hkv, page = k_pool.shape[1], k_pool.shape[2]
    group = hq // hkv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    out = np.zeros((1, hq, t_pad, v_pool.shape[-1]), np.float64)
    for r in range(num_active):
        q_start, q_end = int(cu_q_lens[r]), int(cu_q_lens[r + 1])
        q_len = q_end - q_start
        if q_len <= 0:
            continue
        kv_len = int(kv_lens[r])
        if kv_len < 0:
            out[0, :, q_start:q_end] = np.nan
            continue
        num_pages = -(-kv_len // page)
        rows = np.concatenate(
            [k_pool[page_table[r, p]] for p in range(num_pages)], axis=1
        ) if num_pages else np.zeros((hkv, 0, d))
        vrows = np.concatenate(
            [v_pool[page_table[r, p]] for p in range(num_pages)], axis=1
        ) if num_pages else np.zeros((hkv, 0, v_pool.shape[-1]))
        rows, vrows = rows[:, :kv_len], vrows[:, :kv_len]
        pos = kv_len - q_len + np.arange(q_len)          # (q_len,)
        col = np.arange(kv_len)                          # (kv_len,)
        mask = col[None, :] <= pos[:, None]
        if window is not None:
            band = col[None, :] >= pos[:, None] - (window - 1)
            if sinks is not None:
                band |= col[None, :] < sinks
            mask &= band
        for h in range(hq):
            s = rows[h // group] @ q[0, h, q_start:q_end].T * scale
            s = s.T                                      # (q_len, kv_len)
            if softcap is not None:
                s = softcap * np.tanh(s / softcap)
            s = np.where(mask, s, -np.inf)
            m = np.max(s, axis=-1, keepdims=True)
            m = np.where(np.isfinite(m), m, 0.0)
            p = np.exp(s - m)
            z = np.sum(p, axis=-1, keepdims=True)
            z = np.where(z == 0.0, 1.0, z)
            out[0, h, q_start:q_end] = (p / z) @ vrows[h // group]
    return out
