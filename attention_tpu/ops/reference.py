"""XLA reference attention: the un-fused, compiler-scheduled implementation.

This is the JAX analog of the reference's serial path (`attention.c:20-75`)
— plain QK^T → softmax → V with no manual tiling — but expressed so XLA can
fuse and tile it for the MXU.  It serves three roles:

  1. a second correctness reference (vs the fp64 NumPy oracle) that runs
     on-device;
  2. the differentiable fallback used in training when a custom-VJP flash
     path is not wanted;
  3. the baseline the Pallas flash kernel's speedup is measured against
     (the "MPI baseline" role in the reference's ablation tables,
     README.md:95-102).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from attention_tpu.ops.flash import check_softcap


@functools.partial(jax.jit,
                   static_argnames=("scale", "precision", "softcap"))
def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    precision: str | None = None,
    softcap: float | None = None,
) -> jax.Array:
    """softmax(q k^T * scale) v over the last two axes.

    Shapes: q (..., m, dk), k (..., n, dk), v (..., n, dv).  Leading axes
    broadcast (batch/heads).  Scores and softmax run in float32 regardless
    of input dtype — the mixed-precision boundary the reference implements
    with its d2f/f2d converters (`attention-mpi.c:31-101`): narrow compute
    inside, wider type at the edges.
    """
    check_softcap(softcap)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum(
        "...md,...nd->...mn", q, k, precision=precision,
        preferred_element_type=jnp.float32,
    ) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "...mn,...nd->...md", weights.astype(v.dtype), v, precision=precision,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)


def attention_xla_partials(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    kv_valid=None,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    softcap: float | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Unnormalized attention partials over a local KV shard.

    Returns ``(out_unnorm, row_max, row_sumexp)`` — the same per-shard
    contract as the reference's local flash pass, which leaves each rank
    holding (contrib, lmax, lsum) before the global two-phase normalization
    (`attention-mpi.c:168-189`).  Used by the distributed paths when the
    Pallas kernel is unavailable; all stats in float32.

    ``kv_valid`` (optional dynamic scalar) masks trailing padded KV rows;
    ``causal`` with ``q_offset``/``kv_offset`` applies the global causal
    triangle over shards — both mirror the flash kernel's masking.
    """
    check_softcap(softcap)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    grouped = (
        q.ndim >= 3 and k.ndim >= 3 and q.shape[-3] != k.shape[-3]
    )
    if grouped:
        # GQA: fold Q heads into (kv_heads, group) and contract against the
        # unexpanded K/V — no repeated-KV materialization (the flash kernel
        # achieves the same via its head-group BlockSpec index map)
        hq, hkv = q.shape[-3], k.shape[-3]
        if hq % hkv != 0:
            raise ValueError(
                f"q heads {hq} not a multiple of kv heads {hkv}"
            )
        group = hq // hkv
        qg = q.reshape(*q.shape[:-3], hkv, group, *q.shape[-2:])
        scores = jnp.einsum(
            "...hgmd,...hnd->...hgmn", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale
        scores = scores.reshape(*scores.shape[:-4], hq, *scores.shape[-2:])
    else:
        scores = jnp.einsum(
            "...md,...nd->...mn", q, k, preferred_element_type=jnp.float32
        ) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    masked = False
    if kv_valid is not None:
        col = jnp.arange(k.shape[-2])
        scores = jnp.where(col < kv_valid, scores, -jnp.inf)
        masked = True
    if causal:
        col = jnp.arange(k.shape[-2]) + kv_offset
        row = jnp.arange(q.shape[-2]) + q_offset
        scores = jnp.where(col[None, :] <= row[:, None], scores, -jnp.inf)
        masked = True
    row_max = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - row_max[..., None])
    if masked:
        p = jnp.where(jnp.isneginf(row_max)[..., None], 0.0, p)
    row_sum = jnp.sum(p, axis=-1)
    if grouped:
        pg = p.reshape(*p.shape[:-3], hkv, group, *p.shape[-2:])
        out_unnorm = jnp.einsum(
            "...hgmn,...hnd->...hgmd", pg.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        out_unnorm = out_unnorm.reshape(
            *out_unnorm.shape[:-4], hq, *out_unnorm.shape[-2:]
        )
    else:
        out_unnorm = jnp.einsum(
            "...mn,...nd->...md", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
    return out_unnorm.astype(jnp.float32), row_max, row_sum
