"""Paged KV cache: block-table attention over a shared page pool.

vLLM's core memory idea, built the TPU way: KV lives in a pool of
fixed-size pages (``(num_pages, Hkv, page_size, d)``) shared by every
sequence; a per-sequence page table maps logical cache blocks to
physical pages.  Capacity is pooled — no per-sequence contiguous
reservation, no fragmentation between long and short requests, pages
recycle the moment a sequence finishes.

The kernel is the fused flash-decode kernel with ONE change: the KV
BlockSpec's index map reads the physical page id from the
scalar-prefetched page table instead of computing ``j`` directly —
page translation costs nothing at kernel time because Pallas index
maps already run on prefetched scalars (the same mechanism the ragged
decode uses for per-sequence lengths).  Past-the-prefix grid steps
clamp to the last valid page so their DMAs elide.

Host-side allocation is a free-list (`PagePool`); the jitted decode
loop only ever sees a fixed-shape table, so paging composes with
`lax.scan` token loops (pages for prompt+steps are claimed up front;
the pooling win is ACROSS requests over time).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu import obs
from attention_tpu.ops.decode import (
    banded_block_clamp,
    banded_live,
    check_band,
)
from attention_tpu.ops.flash import (
    _LN2,
    _LOG2E,
    _STAT_LANES,
    NEG_INF,
    _ceil_to,
    _compiler_params,
    _flash_tile,
    _no_stat_kernel,
    _should_interpret,
    check_softcap,
)

# Op-dispatch telemetry (attention_tpu.obs, off by default): one tick
# per host-side dispatch; calls inside an enclosing jit tick per trace.
_PAGED_CALLS = obs.counter(
    "ops.paged.calls",
    "paged decode dispatches by (batch, capacity, dim) bucket")


class PagedKV(NamedTuple):
    """Paged KV state: shared pools + per-sequence translation.

    ``k_pool``/``v_pool``: (P, Hkv, page_size, d).  ``page_table``:
    (B, max_pages) int32 physical page ids (entries past the used
    prefix are ignored).  ``lengths``: (B,) int32 valid tokens.
    """

    k_pool: jax.Array
    v_pool: jax.Array
    page_table: jax.Array
    lengths: jax.Array

    @property
    def length(self):
        """Per-sequence lengths (uniform name across cache types so
        shared code — RoPE offsets — needs no special-casing)."""
        return self.lengths

    @property
    def page_size(self) -> int:
        return self.k_pool.shape[2]

    @property
    def max_tokens(self) -> int:
        return self.page_table.shape[1] * self.page_size


class OutOfPagesError(RuntimeError):
    """`PagePool.alloc` asked for more pages than the free list holds.

    The typed capacity-pressure signal shared by every pool consumer:
    `generate_paged`'s up-front claim surfaces it directly, and the
    serving engine (`attention_tpu.engine`) catches it to trigger
    prefix-cache eviction / admission refusal / preemption-by-recompute
    instead of crashing the step loop.  Subclasses RuntimeError so
    pre-existing callers that caught the bare RuntimeError keep working.
    """


class PageAccountingError(ValueError):
    """Refcount misuse on a `PagePool`: double free, freeing or
    increfing a page that was never allocated, or an out-of-range page
    id.  Always a caller bug — raised instead of silently corrupting
    refcounts (a corrupted refcount recycles a page still referenced by
    a live sequence, which reads as another request's KV).  Subclasses
    ValueError for drop-in compatibility with pre-typed callers."""


class PagePool:
    """Host-side refcounted free-list allocator over ``num_pages``
    physical pages.

    Lives OUTSIDE jit (allocation happens between requests, not between
    tokens); hands out page-id lists that become fixed-shape table
    rows.  ``incref`` supports prefix sharing: a full page referenced
    by several sequences returns to the free list only when every
    reference is freed.
    """

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, -1, -1))
        self._refs = [0] * num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def refcount(self, page: int) -> int:
        """Current reference count of one page (0 = free)."""
        if not (0 <= page < self.num_pages):
            raise PageAccountingError(f"bad page id {page}")
        return self._refs[page]

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise OutOfPagesError(
                f"page pool exhausted: want {n}, free {len(self._free)}"
            )
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def incref(self, pages) -> None:
        """Add a reference to already-allocated pages (prefix sharing)."""
        for p in pages:
            if not (0 <= p < self.num_pages) or self._refs[p] == 0:
                raise PageAccountingError(f"incref of unallocated page {p}")
            self._refs[p] += 1

    def free(self, pages) -> None:
        """Drop one reference per page; recycle at refcount zero."""
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise PageAccountingError(f"bad page id {p}")
            if self._refs[p] == 0:
                raise PageAccountingError(f"double free of page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)

    def table_row(self, pages: list[int], max_pages: int) -> jnp.ndarray:
        """Fixed-width table row; unused entries hold the -1 sentinel
        (the kernel's clamp never reads them; `paged_append` treats a
        -1 target as unclaimed and NaN-poisons loudly)."""
        if len(pages) > max_pages:
            raise ValueError(f"{len(pages)} pages > max_pages {max_pages}")
        return jnp.asarray(pages + [-1] * (max_pages - len(pages)),
                           jnp.int32)


def recommended_page_size(cache_len: int, *, batch: int = 1,
                          heads: int = 1, kv_heads: int | None = None,
                          d: int = 128, dtype=None,
                          window: int | None = None,
                          sinks: int | None = None) -> int:
    """Page size to build a pool with for this serving shape.

    Tuning tables first (`attention_tpu.tuning`, the "paged" family —
    page size IS the paged kernel's tile, so it is what the tuner
    sweeps), then the measured heuristic: the largest power-of-two page
    up to 2048 that divides the capacity (2048 is the bench-measured
    dense-decode streaming block; a page must divide the capacity for
    `paged_from_dense`).  A tuned page that does not divide
    ``cache_len`` falls through to the heuristic rather than producing
    an unusable pool."""
    try:
        from attention_tpu.tuning.lookup import key_fields, lookup

        entry = lookup(
            "paged", dtype=dtype,
            **key_fields("paged", heads=heads, kv_heads=kv_heads,
                         seq=cache_len, dim=d, batch=batch,
                         window=window, sinks=sinks),
        )
        if entry is not None:
            page = int(entry["page_size"])
            if page > 0 and page % 128 == 0 and cache_len % page == 0:
                return page
    except Exception:  # noqa: BLE001 - tuning must never break dispatch
        pass
    for page in (2048, 1024, 512, 256):
        if cache_len % page == 0:
            return page
    return 128


def _paged_kernel(
    lens_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
    acc_scr, m_scr, l_scr,
    *, hkv: int, page: int, softcap2,
    window: int | None = None, sinks: int | None = None,
    chunk: int | None = None,
):
    """One (batch*kv-head, logical-page) grid step.

    ``window``/``sinks``: the same per-sequence [len-w, len) band +
    pinned sink rows as the dense decode kernels — logical positions,
    applied before page translation.  With stat out-refs present the
    kernel emits the unnormalized (contrib, row_max, row_sum) partials
    triple (natural-log domain) instead of normalizing — the merge hook
    for composing the paged band with out-of-band contributions
    (`paged_sink_decode`)."""
    bh = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)
    valid = lens_ref[bh // hkv]
    kv_min = None
    if chunk is None and window is not None:
        kv_min = jnp.maximum(valid - window, 0)
    w_eff = (window + chunk - 1) if (chunk and window) else window

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = banded_live(j, valid, page, w_eff, sinks)

    @pl.when(live)
    def _tile():
        if chunk is None:
            _flash_tile(
                q_ref, k_ref[0], v_ref[0], acc_scr, m_scr, l_scr,
                valid=valid, q_offset=0, kv_offset=0,
                kv_idx=j, q_idx=0,
                n_true=num_j * page, block_k=page, causal=False,
                block_q=q_ref.shape[1], softcap2=softcap2,
                kv_min=kv_min, sinks=sinks,
            )
        else:
            # speculative-verify chunk: rows (g, s) s-minor, row (g, s)
            # at position valid - chunk + s (see decode._decode_kernel)
            _flash_tile(
                q_ref, k_ref[0], v_ref[0], acc_scr, m_scr, l_scr,
                valid=valid, q_offset=valid - chunk, kv_offset=0,
                kv_idx=j, q_idx=0,
                n_true=num_j * page, block_k=page, causal=True,
                block_q=q_ref.shape[1], softcap2=softcap2,
                window=window, sinks=sinks, pos_mod=chunk,
            )

    @pl.when(j == num_j - 1)
    def _finalize():
        l = jnp.max(l_scr[...], axis=-1, keepdims=True)
        if m_out_ref is not None:
            o_ref[0] = acc_scr[...].astype(o_ref.dtype)
            m_out_ref[0] = m_scr[...] * _LN2
            l_out_ref[0] = l_scr[...]
        else:
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "softcap", "window", "sinks",
                     "return_stats"),
)
def _paged_flash_decode_jit(
    q: jax.Array,       # (B, H, d)
    cache: PagedKV,
    *,
    scale: float | None = None,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    return_stats: bool = False,
) -> jax.Array:
    """softmax(q K[:len]^T * scale) V[:len] through the page table.

    ``window``/``sinks``: sliding-window serving with pinned sink rows
    (same per-sequence logical band as :func:`ops.decode.flash_decode`),
    applied before page translation — out-of-window pages are never
    DMA'd, so a windowed server could even free them.

    A 4-D ``q`` (B, H, S, d) switches to speculative-verify chunk mode
    (`ops.decode.flash_decode_chunk` semantics): the S rows are ALREADY
    appended through the page table, ``cache.lengths`` is the
    post-append length, and token s of sequence b attends its causal
    prefix at position ``lengths[b] - S + s`` -> (B, H, S, dv)."""
    check_softcap(softcap)
    check_band(window, sinks)
    s_chunk = None
    if q.ndim == 4:
        s_chunk = q.shape[2]
        if return_stats:
            raise ValueError(
                "return_stats (the paged_sink_decode merge hook) is a "
                "decode-step feature; chunk mode has no sink-merge path"
            )
    b, h, d = q.shape[0], q.shape[1], q.shape[-1]
    p_, hkv, page, dk = cache.k_pool.shape
    dv = cache.v_pool.shape[-1]
    bt, max_pages = cache.page_table.shape
    if dk != d or cache.v_pool.shape[:3] != (p_, hkv, page) or bt != b:
        raise ValueError(
            f"paged cache shapes inconsistent: Q{q.shape} "
            f"K{cache.k_pool.shape} V{cache.v_pool.shape} "
            f"table{cache.page_table.shape}"
        )
    if page % 128:
        raise ValueError(f"page_size {page} must be a multiple of 128")
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens_raw = jnp.broadcast_to(jnp.asarray(cache.lengths, jnp.int32), (b,))
    lens = jnp.maximum(lens_raw, 0)  # poisoned rows read nothing
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    rows = group if s_chunk is None else group * s_chunk
    qs = qs.reshape(b * hkv, rows, d)
    group_pad = _ceil_to(rows, 16)
    if group_pad != rows:
        qs = jnp.pad(qs, ((0, 0), (0, group_pad - rows), (0, 0)))
    w_eff = window if s_chunk is None else (
        None if window is None else window + s_chunk - 1)

    def kv_index(bh, j, lens_ref, tbl_ref):
        # LOGICAL-page clamp (past-the-prefix and, with a window,
        # below-the-band — see decode.banded_block_clamp), THEN page
        # translation, all on prefetched scalars: repeated physical
        # indices make Pallas elide the DMA.
        bi = bh // hkv
        valid = lens_ref[bi]
        jj = banded_block_clamp(j, valid, page, w_eff, sinks)
        # max(..., 0): a length-0 row lands on page_table[bi, 0], which a
        # hand-built PagedKV may legitimately leave as the -1 free-slot
        # sentinel; the output is masked anyway, but the DMA index must
        # stay in bounds.
        return (jnp.maximum(tbl_ref[bi, jj], 0), bh % hkv, 0, 0)

    out_specs = [
        pl.BlockSpec((1, group_pad, dv), lambda bh, j, lr, tr: (bh, 0, 0))
    ]
    out_shapes = [
        jax.ShapeDtypeStruct(
            (b * hkv, group_pad, dv),
            jnp.float32 if return_stats else cache.v_pool.dtype,
        )
    ]
    kernel = functools.partial(
        _paged_kernel, hkv=hkv, page=page,
        softcap2=None if softcap is None else softcap * _LOG2E,
        window=window, sinks=sinks, chunk=s_chunk,
    )
    if return_stats:
        stat_spec = pl.BlockSpec(
            (1, group_pad, _STAT_LANES), lambda bh, j, lr, tr: (bh, 0, 0)
        )
        stat_shape = jax.ShapeDtypeStruct(
            (b * hkv, group_pad, _STAT_LANES), jnp.float32
        )
        out_specs += [stat_spec, stat_spec]
        out_shapes += [stat_shape, stat_shape]
    else:
        # flash.py's splice-None shim works verbatim here: args =
        # (lens, tbl, q, k, v, o, acc, m, l) -> (..., o, None, None, ...)
        kernel = functools.partial(_no_stat_kernel, kernel)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * hkv, max_pages),
        in_specs=[
            pl.BlockSpec((1, group_pad, d),
                         lambda bh, j, lr, tr: (bh, 0, 0)),
            pl.BlockSpec((1, 1, page, d), kv_index),
            pl.BlockSpec((1, 1, page, dv), kv_index),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((group_pad, dv), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
        ],
    )

    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * h * max_pages * page * (d + dv),
            bytes_accessed=b * hkv * max_pages * page * (d + dv)
            * cache.k_pool.dtype.itemsize + qs.size * qs.dtype.itemsize,
            transcendentals=b * h * max_pages * page,
        ),
        interpret=interpret,
    )(lens, cache.page_table, qs, cache.k_pool, cache.v_pool)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]

    if s_chunk is not None:
        out = outs[0][:, :rows].reshape(b, h, s_chunk, dv)
        return jnp.where(lens_raw[:, None, None, None] < 0, jnp.nan,
                         out.astype(jnp.float32)).astype(out.dtype)
    out = outs[0][:, :group].reshape(b, h, dv)
    if return_stats:
        row_max = outs[1][:, :group, 0].reshape(b, h)
        row_sum = outs[2][:, :group, 0].reshape(b, h)
        return out, row_max, row_sum
    # poisoned sequences (negative length, set by a bad append) are NaN
    return jnp.where(lens_raw[:, None, None] < 0, jnp.nan,
                     out.astype(jnp.float32)).astype(out.dtype)


def paged_flash_decode(q: jax.Array, cache: PagedKV,
                       **kwargs) -> jax.Array:
    """Paged decode (telemetry shim; full docs on
    :func:`_paged_flash_decode_jit`)."""
    if obs.is_enabled():
        _PAGED_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[0], cache.max_tokens,
                                    q.shape[-1]),
            entry="chunk" if q.ndim == 4 else "decode")
    return _paged_flash_decode_jit(q, cache, **kwargs)


@functools.partial(
    jax.jit,
    static_argnames=("window", "sinks", "theta", "scale", "softcap",
                     "interpret"),
)
def _paged_sink_decode_jit(
    q: jax.Array,       # (B, H, d)
    cache: PagedKV,
    *,
    window: int,
    sinks: int,
    theta: float = 10000.0,
    scale: float | None = None,
    softcap: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Windowed rope+sinks decode through the page table.

    The blocker this removes: StreamingLLM's in-cache sink positions
    need the sink KEY rows re-rotated by a per-sequence delta, but pool
    pages may be prefix-shared across sequences with different lengths —
    rotating in place would corrupt other readers.  The int8 cache's
    answer (`quant.sink_read_rotation`) is a per-sequence READ COPY of
    just the sink rows; here that copy is a gather of each sequence's
    first logical page's ``sinks`` rows into a tiny dense tensor
    (shared pages stay read-only), rotated by that sequence's own
    ``delta = max(len - (window + sinks), 0)``.

    Composition: the paged kernel computes the window band's partials
    (band rows [max(len-w,0), len) — out-of-band pages never DMA), the
    rotated sink sliver's partials are a few fp32 einsums over
    ``sinks`` rows, and the two merge with the standard online-softmax
    rescale.  Overlap cannot double-count: sink rows inside the band
    (only possible while delta == 0, where rotation is a no-op) are
    masked OUT of the sliver (col < min(sinks, len - w)).
    """
    from attention_tpu.ops.rope import apply_rope

    check_band(window, sinks)
    if sinks is None or window is None:
        raise ValueError("paged_sink_decode requires window and sinks")
    page = cache.page_size
    if sinks > page:
        raise ValueError(
            f"sinks {sinks} > page_size {page}: sink rows must fit the "
            "first logical page"
        )
    b, h, d = q.shape
    hkv = cache.k_pool.shape[1]
    group = h // hkv
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # A: window-band partials through the page table (natural-log stats)
    out_a, m_a, l_a = paged_flash_decode(
        q, cache, scale=scale, softcap=softcap, window=window,
        interpret=interpret, return_stats=True,
    )

    # B: per-sequence read copy of the sink rows, rotated to in-cache
    # positions (the quant.sink_read_rotation pattern at page read)
    lens_raw = jnp.broadcast_to(jnp.asarray(cache.lengths, jnp.int32), (b,))
    lens = jnp.maximum(lens_raw, 0)
    first_phys = jnp.maximum(cache.page_table[:, 0], 0)  # (B,)
    k_sink = cache.k_pool[first_phys, :, :sinks].astype(jnp.float32)
    v_sink = cache.v_pool[first_phys, :, :sinks].astype(jnp.float32)
    delta = jnp.maximum(lens - (window + sinks), 0)
    k_rot = apply_rope(k_sink, delta[:, None, None], theta)
    if group > 1:
        k_rot = jnp.repeat(k_rot, group, axis=1)
        v_sink = jnp.repeat(v_sink, group, axis=1)
    s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32), k_rot) * scale
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)
    kv_min = jnp.maximum(lens - window, 0)
    lim = jnp.minimum(jnp.minimum(sinks, kv_min), lens)  # (B,)
    mask = jnp.arange(sinks)[None, None, :] < lim[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    m_b = jnp.max(s, axis=-1)  # (B, H)
    p = jnp.where(m_b[..., None] == NEG_INF, 0.0, jnp.exp(s - m_b[..., None]))
    l_b = jnp.sum(p, axis=-1)
    out_b = jnp.einsum("bhs,bhsd->bhd", p, v_sink)

    # online merge of the two partial softmaxes
    m = jnp.maximum(m_a, m_b)
    c_a = jnp.where(m_a == NEG_INF, 0.0, jnp.exp(m_a - m))
    c_b = jnp.where(m_b == NEG_INF, 0.0, jnp.exp(m_b - m))
    l = l_a * c_a + l_b * c_b
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (out_a.astype(jnp.float32) * c_a[..., None]
           + out_b * c_b[..., None]) / l_safe[..., None]
    out = jnp.where(lens_raw[:, None, None] < 0, jnp.nan, out)
    return out.astype(cache.v_pool.dtype)


def paged_sink_decode(q: jax.Array, cache: PagedKV, *, window: int,
                      sinks: int, **kwargs) -> jax.Array:
    """Windowed rope+sinks paged decode (telemetry shim; full docs on
    :func:`_paged_sink_decode_jit`)."""
    if obs.is_enabled():
        _PAGED_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[0], cache.max_tokens,
                                    q.shape[-1]),
            entry="sink")
    return _paged_sink_decode_jit(q, cache, window=window, sinks=sinks,
                                  **kwargs)


def paged_append(cache: PagedKV, k_new: jax.Array,
                 v_new: jax.Array) -> PagedKV:
    """Write one new token per sequence (k/v (B, Hkv, 1, d)) at each
    sequence's next slot; returns the updated cache (lengths + 1).

    The slot's physical page must already be in the table (claimed by
    the host-side `PagePool` up front).  Writing past the table's
    capacity OR into an unclaimed (-1) table entry writes NOTHING
    (drop-mode scatter — shared prefix pages stay read-only by
    construction) and marks the sequence's length -1; the decode
    kernel wrapper turns negative lengths into NaN outputs.  Loud,
    contained to the offender, and sticky across further appends.
    """
    page = cache.page_size
    poisoned = cache.lengths < 0
    logical = jnp.maximum(cache.lengths, 0) // page      # (B,)
    slot = jnp.maximum(cache.lengths, 0) % page          # (B,)
    max_pages = cache.page_table.shape[1]
    phys = jnp.take_along_axis(
        cache.page_table, jnp.minimum(logical, max_pages - 1)[:, None],
        axis=1,
    )[:, 0]                                              # (B,)
    bad = (poisoned
           | (cache.lengths >= cache.max_tokens)
           | (phys < 0))
    # drop-mode scatter: bad rows target one-past-the-end (a positive
    # sentinel — negative indices would WRAP before the bounds check)
    phys = jnp.where(bad, cache.k_pool.shape[0], phys)
    k_row = k_new[:, :, 0, :].astype(cache.k_pool.dtype)
    v_row = v_new[:, :, 0, :].astype(cache.v_pool.dtype)
    k_pool = cache.k_pool.at[phys, :, slot].set(k_row, mode="drop")
    v_pool = cache.v_pool.at[phys, :, slot].set(v_row, mode="drop")
    new_lengths = jnp.where(bad, -1, cache.lengths + 1)
    return cache._replace(k_pool=k_pool, v_pool=v_pool,
                          lengths=new_lengths)


def paged_append_chunk(cache: PagedKV, k_new: jax.Array,
                       v_new: jax.Array) -> PagedKV:
    """Write S new tokens per sequence (k/v (B, Hkv, S, d)) at each
    sequence's next slots — the speculative-verify append.

    S single-row appends (S is small and static — the draft lookahead),
    so page-boundary straddles and the unclaimed-page poison contract
    are exactly `paged_append`'s, row by row.  Rollback after rejected
    drafts is a LENGTH rewind (the caller resets ``lengths``): the rows
    stay claimed in the table and are simply overwritten by the next
    chunk — pages never need unclaiming because speculative serving
    claims its full capacity up front (`paged_from_dense`'s
    ``total_pages_per_seq``), the same up-front-claim discipline the
    token loop uses."""
    if (k_new.ndim != 4 or v_new.ndim != 4
            or k_new.shape[:3] != v_new.shape[:3]):
        # head dims may differ (dk != dv caches are supported throughout)
        raise ValueError(
            f"expected (B, Hkv, S, d) chunks: K{k_new.shape} V{v_new.shape}"
        )
    for s in range(k_new.shape[2]):
        cache = paged_append(cache, k_new[:, :, s:s + 1],
                             v_new[:, :, s:s + 1])
    return cache


def paged_from_dense(k_cache: jax.Array, v_cache: jax.Array,
                     lengths, pool: PagePool, *, num_pages: int,
                     page_size: int = 128,
                     total_pages_per_seq: int | None = None) -> PagedKV:
    """Scatter dense (B, Hkv, N, d) prefill caches into a fresh page
    pool: each sequence claims ceil(len/page) pages — or exactly
    ``total_pages_per_seq`` (>= used) to reserve decode headroom up
    front.  Unused table entries hold -1.  One batched scatter per
    pool; the caller keeps the `PagePool` (and the returned table) for
    later free()."""
    import numpy as np

    b, hkv, n, d = k_cache.shape
    if n % page_size:
        raise ValueError(f"capacity {n} not a multiple of {page_size}")
    if page_size % 128:
        raise ValueError(f"page_size {page_size} must be a 128-multiple")
    max_pages = n // page_size
    lengths = jnp.asarray(lengths, jnp.int32)

    host_lens = np.asarray(lengths)
    rows = np.full((b, max_pages), -1, np.int64)
    phys_ids, src_bi, src_lp = [], [], []
    for bi in range(b):
        used = max(int(-(-int(host_lens[bi]) // page_size)), 1)
        total = used if total_pages_per_seq is None else total_pages_per_seq
        if total < used or total > max_pages:
            raise ValueError(
                f"total_pages_per_seq {total} outside [{used}, {max_pages}]"
            )
        pages = pool.alloc(total)
        rows[bi, :total] = pages
        phys_ids.extend(pages[:used])
        src_bi.extend([bi] * used)
        src_lp.extend(range(used))

    # (b, max_pages, hkv, page, d) views -> one gather + one scatter
    src_k = k_cache.reshape(b, hkv, max_pages, page_size, d).transpose(
        0, 2, 1, 3, 4
    )
    src_v = v_cache.reshape(b, hkv, max_pages, page_size, d).transpose(
        0, 2, 1, 3, 4
    )
    ids = jnp.asarray(phys_ids, jnp.int32)
    sb = jnp.asarray(src_bi, jnp.int32)
    sl = jnp.asarray(src_lp, jnp.int32)
    k_pool = jnp.zeros((num_pages, hkv, page_size, d), k_cache.dtype)
    v_pool = jnp.zeros((num_pages, hkv, page_size, d), v_cache.dtype)
    k_pool = k_pool.at[ids].set(src_k[sb, sl])
    v_pool = v_pool.at[ids].set(src_v[sb, sl])
    return PagedKV(k_pool, v_pool, jnp.asarray(rows, jnp.int32), lengths)


def paged_fork(cache: PagedKV, pool: PagePool, src_row: int,
               n_copies: int, *, reserve_pages: int = 0) -> PagedKV:
    """Fork sequence ``src_row`` into ``n_copies`` new sequences that
    SHARE its full prefix pages (vLLM-style prefix sharing).

    Full pages are shared by reference (``pool.incref``); the partial
    tail page — the only page future appends can touch — is physically
    copied per fork, so no copy-on-write is ever needed in the decode
    loop: shared pages are read-only by construction.  Returns a cache
    whose batch is the ``n_copies`` forks (the source row stays valid
    in the original cache and keeps its own references).
    ``reserve_pages`` claims that many extra private pages per fork up
    front so decode appends have headroom.
    """
    import numpy as np

    if n_copies < 1:
        raise ValueError(f"n_copies must be >= 1, got {n_copies}")
    b = cache.page_table.shape[0]
    if not (0 <= src_row < b):
        raise ValueError(f"src_row {src_row} outside [0, {b})")
    page = cache.page_size
    length = int(np.asarray(cache.lengths)[src_row])
    if length < 0:
        raise ValueError(f"src_row {src_row} is poisoned (length < 0)")
    row = np.asarray(cache.page_table[src_row])
    full = length // page
    has_partial = (length % page) != 0
    shared = [int(p) for p in row[:full]]
    max_pages = cache.page_table.shape[1]
    tail_after = full + (1 if has_partial else 0)
    if tail_after + reserve_pages > max_pages:
        raise ValueError(
            f"reserve_pages {reserve_pages} overflows the table "
            f"({tail_after} + {reserve_pages} > {max_pages})"
        )

    # claim everything first WITH rollback, so a mid-fork pool
    # exhaustion cannot leak references or pages
    increfs, allocs = [], []
    rows = np.full((n_copies, max_pages), -1, np.int64)
    try:
        for c in range(n_copies):
            pool.incref(shared)
            increfs.append(shared)
            rows[c, :full] = shared
            nxt = full
            if has_partial:
                tail = pool.alloc(1)[0]
                allocs.append(tail)
                rows[c, full] = tail
                nxt = full + 1
            if reserve_pages:
                extra = pool.alloc(reserve_pages)
                allocs.extend(extra)
                rows[c, nxt : nxt + reserve_pages] = extra
    except Exception:
        for pages in increfs:
            pool.free(pages)
        for p_ in allocs:
            pool.free([p_])
        raise

    k_pool, v_pool = cache.k_pool, cache.v_pool
    if has_partial:
        # one batched scatter: every fork's private tail = src's tail
        src_page = int(row[full])
        ids = jnp.asarray(rows[:, full], jnp.int32)
        k_pool = k_pool.at[ids].set(
            jnp.broadcast_to(k_pool[src_page], (n_copies, *k_pool.shape[1:]))
        )
        v_pool = v_pool.at[ids].set(
            jnp.broadcast_to(v_pool[src_page], (n_copies, *v_pool.shape[1:]))
        )
    lengths = jnp.full((n_copies,), length, jnp.int32)
    return PagedKV(k_pool, v_pool, jnp.asarray(rows, jnp.int32), lengths)
