"""Ragged paged attention: ONE kernel launch for a mixed decode/prefill step.

The serving engine used to lower every step onto two fixed-shape calls —
decode ``(D, 1)`` + prefill-chunk ``(P, S)`` — padded with inactive
poison rows.  This kernel serves the whole step in a single launch over
a PACKED token axis (the tpu_commons ``ragged_paged_attention`` shape):

  * every real token of the step — one per decode request, ``real`` per
    prefill chunk — sits consecutively on one axis of width ``T``;
  * ``cu_q_lens`` (S+1,) delimits each request's token span,
    ``kv_lens`` (S,) holds each request's post-append KV length, and
    ``distribution`` (2,) = (num_decode, num_active) carries the
    decode/prefill split;
  * each request reads KV through its own page-table row, causal within
    the request: the token at span offset ``s`` attends cache positions
    ``<= kv_len - q_len + s``.

The grid is ``(Hkv * S, max_pages)`` — request-slot minor, kv-head
major — so one head's packed output block stays VMEM-resident while
every slot accumulates into its own row span (slots never overlap rows,
so the read-modify-write at finalize composes).  Per-slot KV pages
translate through the scalar-prefetched page table exactly like
`ops.paged`; clamped indices make Pallas elide the DMAs of inactive
slots and past-the-prefix pages, so pad SLOTS cost nothing — the pad
waste of a step is just ``T - total_real`` bucketed tokens, not
``(D - d) + (P*S - real)`` poison rows.

Static tile discipline: the per-request query tile is ``q_tile`` tokens
(>= the longest span; the engine buckets it to a power of two), and
``T`` is pow2-bucketed, so the whole serving life compiles O(log)
executables instead of one per (D, P) composition — the no-recompile-
cliff property the two fixed shapes bought, kept.

``q_tile`` rides in the SHAPE of the cache's ``q_span`` marker field
(shapes are static under jit, values are not) so the engine can pick
the tile per step without threading a static argument through
``model.apply``.

Mesh sharding: both `ragged_paged_append` and `ragged_paged_attention`
are per-KV-head independent — no cross-head reduction anywhere — so
`parallel.serving.head_sharded_ragged_step` runs them inside one
``shard_map`` with the pools and new K/V rows split on the head axis
and every host-packed index array (page table, ``cu_q_lens``,
``kv_lens``, ``distribution``, token placement) replicated verbatim.
Each shard executes this SAME kernel on its contiguous head slice;
zero collectives, and the packed-token axis (and therefore the pad
economics above) is untouched by the shard count.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu import obs
from attention_tpu.ops.decode import (
    banded_block_clamp,
    banded_live,
    check_band,
)
from attention_tpu.ops.flash import (
    _LOG2E,
    _STAT_LANES,
    NEG_INF,
    _compiler_params,
    _should_interpret,
    _softmax_variant_update,
    _tuned_max_mode,
    check_softcap,
)

# Op-dispatch telemetry (attention_tpu.obs, off by default): one tick
# per host-side dispatch; calls inside an enclosing jit tick per trace.
# `ops.ragged.lowered` ticks at TRACE time inside the jitted body and
# records which rescaling-math variant the dispatch actually lowered
# (the ragged equivalent of `ops.flash.lowered`).
_RAGGED_CALLS = obs.counter(
    "ops.ragged.calls",
    "ragged paged-attention dispatches by (tokens, capacity, dim) bucket")
_RAGGED_LOWERED = obs.counter(
    "ops.ragged.lowered",
    "ragged kernel lowerings by requested/resolved max mode")

#: max_mode values the ragged kernel accepts — "bound" is forward-only
#: (it needs the key-norm prefetch this grid does not carry).
RAGGED_MAX_MODES = ("online", "flashd", "amla", "auto")


class RaggedPagedStep(NamedTuple):
    """One packed engine step over the shared page pool.

    ``k_pool``/``v_pool``: (P, Hkv, page_size, d) — the same pools the
    two-call engine steps.  ``page_table``: (S, max_pages) int32, one
    row per request SLOT (inactive slots all -1).  ``kv_lens``: (S,)
    int32 valid cache tokens per slot — PRE-append when handed to
    `ragged_paged_append`, post-append after it (-1 = poisoned).
    ``cu_q_lens``: (S+1,) int32 cumulative token spans; slot ``s`` owns
    packed tokens ``[cu[s], cu[s+1])``.  ``distribution``: (2,) int32
    (num_decode_slots, num_active_slots); decode slots come first.
    ``token_pos``: (T,) int32 absolute cache position of each packed
    token (drives RoPE and the append scatter).  ``token_slot``: (T,)
    int32 owning slot per token, -1 for pad tokens.  ``q_span``: a
    (q_tile,) int32 zeros marker whose SHAPE carries the static
    per-request query-tile width (values unused).
    """

    k_pool: jax.Array
    v_pool: jax.Array
    page_table: jax.Array
    kv_lens: jax.Array
    cu_q_lens: jax.Array
    distribution: jax.Array
    token_pos: jax.Array
    token_slot: jax.Array
    q_span: jax.Array

    @property
    def length(self):
        """Per-slot lengths (uniform name across cache types)."""
        return self.kv_lens

    @property
    def page_size(self) -> int:
        return self.k_pool.shape[2]

    @property
    def q_tile(self) -> int:
        return self.q_span.shape[0]

    @property
    def max_tokens(self) -> int:
        return self.page_table.shape[1] * self.page_size


def packed_bucket(n_tokens: int, *, minimum: int = 8) -> int:
    """Packed-axis width for ``n_tokens`` real tokens.

    Two tiers per octave: the next power of two, refined down to the
    3·2^k midpoint (8, 16, 24, 32, 48, 64, 96, ...) when the midpoint
    still covers ``n_tokens`` and keeps the width 8-aligned (so
    ``width * group`` stays sublane-legal for every GQA group).  The
    midpoint tier halves the worst-case pow2 pad tail (a 33-token step
    pads to 48, not 64) while only DOUBLING the signature count — still
    O(log max_tokens) distinct jit shapes over a serving life, the
    no-recompile-cliff property the pow2 buckets bought.  Idempotent:
    every returned width buckets to itself."""
    if n_tokens < 0:
        raise ValueError(f"n_tokens must be >= 0, got {n_tokens}")
    w = max(minimum, 1)
    while w < n_tokens:
        w *= 2
    mid = 3 * w // 4
    if w >= 4 and mid >= n_tokens and mid >= max(minimum, 1) \
            and mid % 8 == 0:
        w = mid
    return w


def tile_tokens(max_q_len: int, group: int) -> int:
    """Smallest query tile (in tokens) covering ``max_q_len`` whose row
    count ``tile * group`` hits the fp32 sublane granule (8)."""
    if group < 1:
        raise ValueError(f"group must be >= 1, got {group}")
    t = max(int(max_q_len), 1)
    while (t * group) % 8:
        t += 1
    return t


def recommended_q_tile(max_q_len: int, group: int, *, heads: int = 1,
                       kv_heads: int | None = None, seq: int = 0,
                       dim: int = 0, batch: int = 1,
                       dtype=None) -> int:
    """Static query-tile width (tokens) for a step whose longest span
    is ``max_q_len``: pow2-bucketed for jit-signature reuse, sublane-
    aligned, optionally widened toward the tuned ``ragged`` family
    ``block_q`` row count when the measured-dispatch tables ship one."""
    t = packed_bucket(max_q_len, minimum=1)
    try:
        from attention_tpu.tuning.lookup import key_fields, lookup

        entry = lookup(
            "ragged", dtype=dtype,
            **key_fields("ragged", heads=heads, kv_heads=kv_heads,
                         seq=seq, dim=dim, batch=batch),
        )
        if entry is not None:
            cap = int(entry["block_q"]) // max(group, 1)
            if cap >= max_q_len:
                t = min(t, cap)
    except Exception:  # noqa: BLE001 - tuning must never break dispatch
        pass
    return tile_tokens(t, group)


def _ragged_kernel(
    lens_ref, cu_ref, dist_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
    acc_scr, m_scr, l_scr,
    *, s_slots: int, group: int, page: int, q_tile: int, t_pad: int,
    softcap2, window: int | None, sinks: int | None,
    variant: str = "online",
):
    """One (kv-head * slot, logical-page) grid step.

    The output block is the head's FULL packed row axis, index-mapped
    constant over (slot, page), so it stays VMEM-resident while every
    slot finalizes its own row span into it — the single-launch analog
    of one out-block per decode row.  Slot spans never overlap, and the
    grid is sequential over slots ("arbitrary" semantics), so the
    masked read-modify-write at finalize is race-free."""
    rh = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)
    r = jax.lax.rem(rh, s_slots)
    q_rows = q_tile * group
    raw_len = lens_ref[r]
    kv_len = jnp.maximum(raw_len, 0)  # poisoned slots read nothing
    q_start = cu_ref[r]
    q_len = cu_ref[r + 1] - q_start
    active = jnp.logical_and(r < dist_ref[1], q_len > 0)
    # tile start: the span head, clamped so the tile stays in-bounds
    # (q_len <= q_tile by the caller contract, so the span always fits)
    clamp = jnp.minimum(q_start, t_pad - q_tile)
    # the band must admit the EARLIEST query row's window; per-row
    # exactness comes from the mask below (the decode kernels' chunk rule)
    w_eff = (window + q_tile - 1) if window is not None else None

    @pl.when(jnp.logical_and(r == 0, j == 0))
    def _zero_out():
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = jnp.logical_and(active,
                           banded_live(j, kv_len, page, w_eff, sinks))

    @pl.when(live)
    def _tile():
        qb = q_ref[0, pl.ds(clamp * group, q_rows), :]
        s = jax.lax.dot_general(
            qb, k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (q_rows, page), log2-domain (q pre-scaled by scale*log2e)
        if softcap2 is not None:
            s = softcap2 * jnp.tanh(s / softcap2)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        seg = clamp + row // group - q_start   # span offset per row
        pos = kv_len - q_len + seg             # absolute cache position
        col = j * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = jnp.logical_and(
            jnp.logical_and(seg >= 0, seg < q_len), col <= pos
        )
        if window is not None:
            win = col >= pos - (window - 1)
            if sinks is not None:
                win = jnp.logical_or(win, col < sinks)
            mask = jnp.logical_and(mask, win)
        s = jnp.where(mask, s, NEG_INF)
        p, update_acc = _softmax_variant_update(
            s, m_scr, l_scr, variant=variant, masked=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = update_acc(acc_scr[...], pv)

    @pl.when(jnp.logical_and(j == num_j - 1, active))
    def _finalize():
        if variant == "flashd":
            # the accumulator is already normalized (flashd's hidden
            # division) — the per-slot epilogue loses its divide
            res = acc_scr[...]
        else:
            l = jnp.max(l_scr[...], axis=-1, keepdims=True)
            l_safe = jnp.where(l == 0.0, 1.0, l)
            res = acc_scr[...] / l_safe
        # poisoned slots (bad append, length -1) emit NaN, loudly
        res = jnp.where(raw_len < 0, jnp.nan, res)
        row = jax.lax.broadcasted_iota(jnp.int32, res.shape, 0)
        seg = clamp + row // group - q_start
        mine = jnp.logical_and(seg >= 0, seg < q_len)
        cur = o_ref[0, pl.ds(clamp * group, q_rows), :]
        o_ref[0, pl.ds(clamp * group, q_rows), :] = jnp.where(
            mine, res, cur.astype(jnp.float32)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "softcap", "window", "sinks",
                     "max_mode"),
)
def _ragged_paged_attention_jit(
    q: jax.Array,            # (1, Hq, T, d) packed token axis
    cache: RaggedPagedStep,
    *,
    scale: float | None = None,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    max_mode: str = "online",
) -> jax.Array:
    """softmax(q K^T * scale) V for every packed token through its
    slot's page table, causal within each request — (1, Hq, T, dv).

    ``kv_lens`` must be POST-append (run `ragged_paged_append` first);
    pad tokens return zeros, poisoned slots NaN.  ``window``/``sinks``
    are the decode kernels' per-request logical band, applied before
    page translation so out-of-window pages never DMA.  ``max_mode``
    picks the rescaling math ("online"/"flashd"/"amla" — the per-slot
    masked read-modify-write finalize is exactly the epilogue flashd
    and amla cheapen); "auto" consults the tuning tables (ragged
    family) and falls back to "online"."""
    check_softcap(softcap)
    check_band(window, sinks)
    if q.ndim != 4 or q.shape[0] != 1:
        raise ValueError(
            f"packed q must be (1, Hq, T, d), got {q.shape}"
        )
    _, h, t_pad, d = q.shape
    p_, hkv, page, dk = cache.k_pool.shape
    dv = cache.v_pool.shape[-1]
    s_slots, max_pages = cache.page_table.shape
    if dk != d or cache.v_pool.shape[:3] != (p_, hkv, page):
        raise ValueError(
            f"ragged cache shapes inconsistent: Q{q.shape} "
            f"K{cache.k_pool.shape} V{cache.v_pool.shape}"
        )
    if cache.cu_q_lens.shape != (s_slots + 1,):
        raise ValueError(
            f"cu_q_lens {cache.cu_q_lens.shape} must be "
            f"({s_slots + 1},) for a {s_slots}-slot table"
        )
    if page % 128:
        raise ValueError(f"page_size {page} must be a multiple of 128")
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    group = h // hkv
    q_tile = cache.q_tile
    if (t_pad * group) % 8 or (q_tile * group) % 8:
        raise ValueError(
            f"packed width {t_pad} and q_tile {q_tile} must keep "
            f"token*group row counts 8-aligned (group {group}); use "
            "packed_bucket/tile_tokens"
        )
    if q_tile > t_pad:
        raise ValueError(f"q_tile {q_tile} > packed width {t_pad}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    if max_mode not in RAGGED_MAX_MODES:
        raise ValueError(
            f"unknown ragged max_mode {max_mode!r}; one of "
            f"{RAGGED_MAX_MODES} (bound mode is forward-only)")
    variant = max_mode
    if variant == "auto":
        variant = _tuned_max_mode(
            "ragged", dtype=q.dtype, allowed=("online", "flashd", "amla"),
            heads=h, kv_heads=hkv, seq=cache.max_tokens, dim=d,
            batch=s_slots, window=window, sinks=sinks)
    if obs.is_enabled():
        _RAGGED_LOWERED.inc(requested=max_mode, lowered=variant)

    lens = jnp.asarray(cache.kv_lens, jnp.int32)
    cu = jnp.asarray(cache.cu_q_lens, jnp.int32)
    dist = jnp.asarray(cache.distribution, jnp.int32)
    # token-major packed rows: row = token * group + group_head, so a
    # span's rows are contiguous and the per-slot tile is one dynamic
    # sublane slice
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    qs = qs[0].reshape(hkv, group, t_pad, d).transpose(0, 2, 1, 3)
    qs = qs.reshape(hkv, t_pad * group, d)
    w_eff = (window + q_tile - 1) if window is not None else None

    def kv_index(rh, j, lens_ref, cu_ref, dist_ref, tbl_ref):
        # LOGICAL-page clamp (past-the-prefix, and below-the-band with
        # a window), THEN page translation, all on prefetched scalars:
        # repeated physical indices make Pallas elide the DMA — pad
        # slots (length 0) pin to one page and never re-fetch.
        r = jax.lax.rem(rh, s_slots)
        valid = jnp.maximum(lens_ref[r], 0)
        jj = banded_block_clamp(j, valid, page, w_eff, sinks)
        return (jnp.maximum(tbl_ref[r, jj], 0), rh // s_slots, 0, 0)

    q_rows = q_tile * group
    kernel = functools.partial(
        _ragged_kernel, s_slots=s_slots, group=group, page=page,
        q_tile=q_tile, t_pad=t_pad,
        softcap2=None if softcap is None else softcap * _LOG2E,
        window=window, sinks=sinks, variant=variant,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(hkv * s_slots, max_pages),
        in_specs=[
            pl.BlockSpec((1, t_pad * group, d),
                         lambda rh, j, lr, cr, dr, tr: (rh // s_slots,
                                                        0, 0)),
            pl.BlockSpec((1, 1, page, d), kv_index),
            pl.BlockSpec((1, 1, page, dv), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, t_pad * group, dv),
                         lambda rh, j, lr, cr, dr, tr: (rh // s_slots,
                                                        0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_rows, dv), jnp.float32),
            pltpu.VMEM((q_rows, _STAT_LANES), jnp.float32),
            pltpu.VMEM((q_rows, _STAT_LANES), jnp.float32),
        ],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hkv, t_pad * group, dv),
                                 cache.v_pool.dtype),
        ],
        # NOT parallel: every slot of one head accumulates into the
        # same resident output block
        compiler_params=_compiler_params(("arbitrary", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * hkv * s_slots * q_rows * max_pages * page
            * (d + dv),
            bytes_accessed=hkv * s_slots * max_pages * page * (d + dv)
            * cache.k_pool.dtype.itemsize + qs.size * qs.dtype.itemsize,
            transcendentals=hkv * s_slots * q_rows * max_pages * page,
        ),
        interpret=interpret,
    )(lens, cu, dist, cache.page_table, qs, cache.k_pool, cache.v_pool)
    out = outs[0] if isinstance(outs, (list, tuple)) else outs
    out = out.reshape(hkv, t_pad, group, dv).transpose(0, 2, 1, 3)
    return out.reshape(1, h, t_pad, dv)


def ragged_paged_attention(q: jax.Array, cache: RaggedPagedStep,
                           **kwargs) -> jax.Array:
    """Ragged paged attention (telemetry shim; full docs on
    :func:`_ragged_paged_attention_jit`)."""
    if obs.is_enabled():
        _RAGGED_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[2], cache.max_tokens,
                                    q.shape[-1]))
    return _ragged_paged_attention_jit(q, cache, **kwargs)


def ragged_paged_append(cache: RaggedPagedStep, k_new: jax.Array,
                        v_new: jax.Array) -> RaggedPagedStep:
    """Write every packed token's K/V row (k/v (1, Hkv, T, d)) at its
    slot's next positions; returns the cache with post-append lengths.

    One vectorized drop-mode scatter over the token axis — the packed
    analog of `ops.paged.paged_append`, with the same poison contract:
    a token targeting an unclaimed (-1) table entry or past the table's
    capacity writes NOTHING and marks its whole SLOT's length -1
    (sticky; the attention kernel then emits NaN for that slot's
    tokens).  Pad tokens (slot -1) always drop, silently."""
    page = cache.page_size
    t = k_new.shape[2]
    if (k_new.ndim != 4 or v_new.ndim != 4
            or k_new.shape[:3] != v_new.shape[:3]
            or k_new.shape[0] != 1
            or t != cache.token_slot.shape[0]):
        raise ValueError(
            f"expected (1, Hkv, {cache.token_slot.shape[0]}, d) packed "
            f"rows: K{k_new.shape} V{v_new.shape}"
        )
    s_slots, max_pages = cache.page_table.shape
    slot = jnp.asarray(cache.token_slot, jnp.int32)
    pos = jnp.asarray(cache.token_pos, jnp.int32)
    safe_slot = jnp.maximum(slot, 0)
    logical = pos // page
    phys = cache.page_table[safe_slot,
                            jnp.minimum(logical, max_pages - 1)]
    bad = ((phys < 0)
           | (logical >= max_pages)
           | (cache.kv_lens[safe_slot] < 0))
    drop = jnp.logical_or(bad, slot < 0)
    # drop-mode scatter: dropped tokens target one-past-the-end (a
    # positive sentinel — negative indices would WRAP before the check)
    tgt = jnp.where(drop, cache.k_pool.shape[0], phys)
    k_rows = k_new[0].transpose(1, 0, 2).astype(cache.k_pool.dtype)
    v_rows = v_new[0].transpose(1, 0, 2).astype(cache.v_pool.dtype)
    k_pool = cache.k_pool.at[tgt, :, pos % page].set(k_rows, mode="drop")
    v_pool = cache.v_pool.at[tgt, :, pos % page].set(v_rows, mode="drop")
    # per-slot sticky poison: any bad REAL token condemns its slot
    bad_slot = jnp.zeros((s_slots + 1,), jnp.bool_).at[
        jnp.where(slot < 0, s_slots, slot)
    ].max(bad, mode="drop")[:s_slots]
    q_lens = cache.cu_q_lens[1:] - cache.cu_q_lens[:-1]
    new_lens = jnp.where(bad_slot | (cache.kv_lens < 0), -1,
                         cache.kv_lens + q_lens)
    return cache._replace(k_pool=k_pool, v_pool=v_pool,
                          kv_lens=new_lens)


__all__ = [
    "RaggedPagedStep",
    "ragged_paged_attention",
    "ragged_paged_append",
    "packed_bucket",
    "tile_tokens",
    "recommended_q_tile",
]
