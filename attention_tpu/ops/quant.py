"""int8-quantized KV cache + fused quantized flash-decode kernel.

Cuts the KV cache's HBM footprint to 0.63x bf16 (int8 values at 0.5x
plus 32B/row of replicated fp32 scales against 128B/row saved) — more
context per chip for free accuracy-wise (~4e-4 output error measured
at seq=32k).

Quantization scheme: symmetric per-token absmax (one fp32 scale per
cached row per head).  The kernel never dequantizes into (block_k, d)
fp tiles via per-row multiplies: a per-token scale is a scalar on the
contraction's token axis, so it commutes out of both matmuls —

    scores = q · (K_q · s_K)ᵀ = (q · K_qᵀ) ∘ s_K     (row-vec, post-matmul)
    out    = p · (V_q · s_V)  = (p ∘ s_V) · V_q       (folded into P)

and the token axis lies along *lanes* of the score/probability tiles,
so the scales apply as (1, block_k) row vectors — no narrow-block
transposes.  Scales ship sublane-replicated (8, N) per (batch, kv head)
(a (1, block_k) vector block would violate Mosaic's (8, 128) min-tile
rule; the 8x replication costs 32B/row against the 224B/row saved).

**Storage is plain int8** (B, Hkv, N, d): blocks DMA at full rate on
the current Mosaic toolchain and dequant is one int8->bf16 convert per
tile.  (An earlier revision stored byte-planar int32 words to dodge a
since-fixed ~10x int8-DMA slowdown — see git history if it ever
regresses; measured now: int8 blocks stream FASTER than bf16 per
block, and the planar unpack's 12 VPU ops/tile made decode ~1.7x
slower than bf16 instead of at parity.)

The reference's mixed-precision boundary (fp64 edges / fp32 compute +
wire, `attention-mpi.c:31-101`) pushed one level further: bf16 compute,
int8 storage.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu.ops.decode import (
    _pick_block_k,
    banded_block_clamp,
    banded_live,
    check_band,
)
from attention_tpu.ops.flash import (
    banded_keep,
    _LOG2E,
    _STAT_LANES,
    NEG_INF,
    _ceil_to,
    _compiler_params,
    _online_softmax_update,
    _should_interpret,
    check_softcap,
)


class QuantizedKV(NamedTuple):
    """int8 KV cache: values (B, Hkv, N, d) int8 + per-token fp32
    scales stored sublane-replicated (B, Hkv, 8, N)."""

    k_q: jax.Array
    k_scale: jax.Array
    v_q: jax.Array
    v_scale: jax.Array

    @property
    def capacity(self) -> int:
        return self.k_q.shape[2]

    @property
    def head_dim(self) -> int:
        return self.k_q.shape[3]


def _quant_rows(x):
    """Symmetric per-token absmax int8 -> (int8 values, (..., 8, N) scales)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)  # (..., N)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    scale_rep = jnp.broadcast_to(
        scale[..., None, :], (*scale.shape[:-1], 8, scale.shape[-1])
    )
    return q, scale_rep


def sink_read_rotation(kv: "QuantizedKV", new_total, window: int,
                       sinks: int, theta: float) -> "QuantizedKV":
    """StreamingLLM in-cache sink positions for an int8 cache, at read
    time: dequantize the ``sinks`` pinned key rows, rotate them forward
    by ``delta = max(new_total - (window + sinks), 0)`` (the same
    convention as the bf16 `_sink_read_keys` — RoPE rotations compose
    additively), requantize, and return a READ copy of the cache; the
    stored cache keeps absolute rotations, so there is no compounding
    drift.  Double quantization of the sink rows adds int8-grade noise,
    inside the cache's existing error contract.
    """
    from attention_tpu.ops.rope import apply_rope

    k_sink = (kv.k_q[:, :, :sinks].astype(jnp.float32)
              * kv.k_scale[:, :, 0, :sinks][..., None])
    delta = jnp.maximum(
        jnp.asarray(new_total, jnp.int32) - (window + sinks), 0
    )
    if delta.ndim:  # ragged per-sequence totals -> (B, 1, 1) positions
        delta = delta[:, None, None]
    q_rot, s_rot = _quant_rows(apply_rope(k_sink, delta, theta))
    zero = jnp.zeros((), jnp.int32)
    return kv._replace(
        k_q=jax.lax.dynamic_update_slice(
            kv.k_q, q_rot, (zero, zero, zero, zero)
        ),
        k_scale=jax.lax.dynamic_update_slice(
            kv.k_scale, s_rot, (zero, zero, zero, zero)
        ),
    )


def quantize_kv(k: jax.Array, v: jax.Array) -> QuantizedKV:
    """Quantize full (B, Hkv, N, d) K/V caches to the int8 cache format."""
    k_q, k_s = _quant_rows(k)
    v_q, v_s = _quant_rows(v)
    return QuantizedKV(k_q, k_s, v_q, v_s)


def update_quantized_kv(cache: QuantizedKV, k_new: jax.Array,
                        v_new: jax.Array, index) -> QuantizedKV:
    """Write S new rows (B, Hkv, S, d) at ``index`` (dynamic scalar).

    Overflow (index + S > capacity) NaN-poisons the written scales —
    dynamic_update_slice would otherwise clamp the start index and
    silently destroy earlier rows (same contract as the bf16
    ``KVCache`` path, models/attention_layer.py).
    """
    k_q, k_s = _quant_rows(k_new)
    v_q, v_s = _quant_rows(v_new)
    overflow = index + k_new.shape[2] > cache.capacity
    k_s = jnp.where(overflow, jnp.nan, k_s)
    v_s = jnp.where(overflow, jnp.nan, v_s)
    zero = jnp.zeros((), jnp.int32)
    return QuantizedKV(
        jax.lax.dynamic_update_slice(cache.k_q, k_q, (zero, zero, index, zero)),
        jax.lax.dynamic_update_slice(cache.k_scale, k_s, (zero, zero, zero, index)),
        jax.lax.dynamic_update_slice(cache.v_q, v_q, (zero, zero, index, zero)),
        jax.lax.dynamic_update_slice(cache.v_scale, v_s, (zero, zero, zero, index)),
    )


def _decode_q_kernel(
    lens_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
    acc_scr, m_scr, l_scr,
    *, hkv: int, block_k: int, softcap2: float | None = None,
    window: int | None = None, sinks: int | None = None,
    chunk: int | None = None, unpack=None,
):
    """One (batch*kv-head, kv-block) grid step of quantized-cache
    decode (int8, and int4 via ``unpack``).

    ``window``/``sinks``: the same per-sequence [len-w, len) band +
    pinned sink rows as the bf16 decode kernel (ops/decode.py).
    ``chunk``: speculative-verify mode, mirroring
    `decode._decode_kernel`: rows pack (group, chunk) with s minor,
    row (g, s) at position ``valid - chunk + s``, causal + per-row
    window band.  ``unpack``: tile dequantizer (storage block -> bf16
    values block); None = plain int8 convert.  ONE kernel body serves
    every BYTE-PER-FEATURE storage format so masking/band logic cannot
    drift between them.  Documented exception: the token-paired int4
    layout (`_decode_tok4_kernel`) cannot ride the unpack hook — its
    unpack doubles the ROW count, changing the score tile's lane->token
    map — so it mirrors this body instead; any band/mask semantics
    change here must touch that kernel too, and the cross-layout
    equality tests (tests/test_quant.py::test_int4_tok_matches_feature_
    layout, tpu_smoke's token-paired case) pin the two against drift."""
    bh = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)
    valid = lens_ref[bh // hkv]
    kv_min = None
    if chunk is None and window is not None:
        kv_min = jnp.maximum(valid - window, 0)
    w_eff = (window + chunk - 1) if (chunk and window) else window

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = banded_live(j, valid, block_k, w_eff, sinks)

    deq = ((lambda x: x.astype(jnp.bfloat16)) if unpack is None
           else unpack)

    @pl.when(live)
    def _tile():
        q = q_ref[0]                       # (group_pad, d), log2-prescaled
        kq = deq(k_ref[0])                 # (block_k, d) bf16 values
        s = jax.lax.dot_general(
            q, kq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        k_scale = jnp.max(ks_ref[0], axis=0, keepdims=True)  # (1, block_k)
        s = s * k_scale                     # dequant on the score tile
        if softcap2 is not None:
            # logit soft-capping in log2 units (see flash.py::_flash_tile)
            s = softcap2 * jnp.tanh(s / softcap2)
        col = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < valid
        if chunk is not None:
            # per-row chunk position: causal + window band per row
            pos = valid - chunk + jax.lax.rem(
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0), chunk
            )
            mask = jnp.logical_and(mask, col <= pos)
            if window is not None:
                keep = col >= pos - (window - 1)
                if sinks is not None:
                    keep = jnp.logical_or(keep, col < sinks)
                mask = jnp.logical_and(mask, keep)
        elif kv_min is not None:
            mask = jnp.logical_and(mask, banded_keep(col, kv_min, sinks))
        s = jnp.where(mask, s, NEG_INF)

        p, corr = _online_softmax_update(s, m_scr, l_scr, masked=True)
        v_scale = jnp.max(vs_ref[0], axis=0, keepdims=True)  # (1, block_k)
        pv = jax.lax.dot_general(
            (p * v_scale).astype(jnp.bfloat16),   # dequant folded into P
            deq(v_ref[0]),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr + pv

    @pl.when(j == num_j - 1)
    def _finalize():
        l = jnp.max(l_scr[...], axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_k", "interpret", "softcap", "window",
                     "sinks"),
)
def flash_decode_quantized(
    q: jax.Array,          # (B, H, d)
    cache: QuantizedKV,    # int8 caches + scales
    lengths: jax.Array,    # (B,) int32 or scalar
    *,
    scale: float | None = None,
    block_k: int = 4096,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """softmax(q K[:len]^T * scale) V[:len] against an int8 cache.

    ``softcap`` applies Gemma-2-style logit capping before softmax.
    ``window``/``sinks``: sliding-window serving with pinned sink rows,
    same per-sequence band semantics as :func:`ops.decode.flash_decode`.
    Default ``block_k`` is 4096 — measured 445 us vs 519 at 2048 for a
    32k cache (device clock), which is exactly the 0.625x byte ratio of
    int8+scales vs bf16: the int8 stream needs the bigger block to stay
    bandwidth-proportional (the bf16 kernel is already at HBM peak with
    2048).
    """
    check_softcap(softcap)
    check_band(window, sinks)
    b, h, d = q.shape
    bk_, hkv, n, dk_ = cache.k_q.shape
    if bk_ != b or dk_ != d or cache.v_q.shape != (b, hkv, n, d):
        raise ValueError(
            f"cache shapes inconsistent: Q{q.shape} K{cache.k_q.shape} "
            f"V{cache.v_q.shape}"
        )
    if cache.k_scale.shape != (b, hkv, 8, n) or \
            cache.v_scale.shape != (b, hkv, 8, n):
        raise ValueError(
            f"scale shapes {cache.k_scale.shape}/{cache.v_scale.shape} "
            f"!= {(b, hkv, 8, n)}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(jnp.bfloat16)
    qs = qs.reshape(b * hkv, group, d)
    group_pad = _ceil_to(group, 16)
    if group_pad != group:
        qs = jnp.pad(qs, ((0, 0), (0, group_pad - group), (0, 0)))

    block_k = _pick_block_k(n, block_k)
    kc = cache.k_q.reshape(b * hkv, n, d)
    vc = cache.v_q.reshape(b * hkv, n, d)
    ks = cache.k_scale.reshape(b * hkv, 8, n)
    vs = cache.v_scale.reshape(b * hkv, 8, n)

    def kv_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, banded_block_clamp(j, valid, block_k, window, sinks), 0)

    def scale_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, 0, banded_block_clamp(j, valid, block_k, window, sinks))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n // block_k),
        in_specs=[
            pl.BlockSpec((1, group_pad, d), lambda bh, j, lr: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, 8, block_k), scale_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, 8, block_k), scale_index),
        ],
        out_specs=pl.BlockSpec((1, group_pad, d), lambda bh, j, lr: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group_pad, d), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_q_kernel, hkv=hkv, block_k=block_k,
            softcap2=None if softcap is None else softcap * _LOG2E,
            window=window, sinks=sinks,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, group_pad, d), jnp.bfloat16),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * n * d,
            bytes_accessed=kc.size + vc.size + (ks.size + vs.size) * 4
            + qs.size * 2,
            transcendentals=b * h * n,
        ),
        interpret=interpret,
    )(lens, qs, kc, ks, vc, vs)

    return out[:, :group].reshape(b, h, d)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_k", "interpret", "softcap", "window",
                     "sinks"),
)
def flash_decode_quantized_chunk(
    q: jax.Array,          # (B, H, S, d) — S new tokens per sequence
    cache: QuantizedKV,    # chunk rows ALREADY appended (int8)
    new_lengths: jax.Array,  # (B,) int32 lengths AFTER the append
    *,
    scale: float | None = None,
    block_k: int = 4096,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """Score S appended tokens against the int8 cache in one stream
    -> (B, H, S, d): the speculative-verify primitive on the quantized
    cache (`ops.decode.flash_decode_chunk`'s layout and masking, this
    module's scales-commute-out dequantization)."""
    check_softcap(softcap)
    check_band(window, sinks)
    if q.ndim != 4:
        raise ValueError(f"expected q (B,H,S,d), got {q.shape}")
    b, h, s_chunk, d = q.shape
    bk_, hkv, n, dk_ = cache.k_q.shape
    if bk_ != b or dk_ != d or cache.v_q.shape != (b, hkv, n, d):
        raise ValueError(
            f"cache shapes inconsistent: Q{q.shape} K{cache.k_q.shape} "
            f"V{cache.v_q.shape}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens = jnp.broadcast_to(jnp.asarray(new_lengths, jnp.int32), (b,))
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(jnp.bfloat16)
    qs = qs.reshape(b * hkv, group * s_chunk, d)
    rows = group * s_chunk
    rows_pad = _ceil_to(rows, 16)
    if rows_pad != rows:
        qs = jnp.pad(qs, ((0, 0), (0, rows_pad - rows), (0, 0)))

    block_k = _pick_block_k(n, block_k)
    kc = cache.k_q.reshape(b * hkv, n, d)
    vc = cache.v_q.reshape(b * hkv, n, d)
    ks = cache.k_scale.reshape(b * hkv, 8, n)
    vs = cache.v_scale.reshape(b * hkv, 8, n)
    w_eff = None if window is None else window + s_chunk - 1

    def kv_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, banded_block_clamp(j, valid, block_k, w_eff, sinks), 0)

    def scale_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, 0, banded_block_clamp(j, valid, block_k, w_eff, sinks))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n // block_k),
        in_specs=[
            pl.BlockSpec((1, rows_pad, d), lambda bh, j, lr: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, 8, block_k), scale_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, 8, block_k), scale_index),
        ],
        out_specs=pl.BlockSpec((1, rows_pad, d), lambda bh, j, lr: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows_pad, d), jnp.float32),
            pltpu.VMEM((rows_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((rows_pad, _STAT_LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_q_kernel, hkv=hkv, block_k=block_k,
            softcap2=None if softcap is None else softcap * _LOG2E,
            window=window, sinks=sinks, chunk=s_chunk,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, rows_pad, d), jnp.bfloat16),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * s_chunk * n * d,
            bytes_accessed=kc.size + vc.size + (ks.size + vs.size) * 4
            + qs.size * 2,
            transcendentals=b * h * s_chunk * n,
        ),
        interpret=interpret,
    )(lens, qs, kc, ks, vc, vs)

    return out[:, :rows].reshape(b, h, s_chunk, d)


# ---------------------------------------------------------------------------
# int4 KV cache (round 5): half the int8 value bytes.  Decode sits at
# frac 1.00 of the measured HBM streaming ceiling (BENCH_r04), so the
# only remaining currency is bytes streamed — int4 cuts the VALUE
# stream to 0.25x bf16; with the 32B/row replicated fp32 scales the
# total at d=128 is (64+32)/256 = 0.375x bf16 (0.6x of int8's 0.625x
# — the fixed scale bytes dilute the nibble saving; bench.py's
# int4_bytes accounting uses the same formula).
#
# Packing: two int4 values per int8 byte along the FEATURE dim, split
# halves — byte f of a row holds feature f in its low nibble and
# feature f + d/2 in its high nibble, so the in-kernel unpack is a few
# float floor/fma ops and a lane concat (lo half ++ hi half restores
# natural feature order — no interleave relayout, the trap that made
# the byte-planar int8 experiment 1.7x slower, see module docstring).
# Scales stay per-token symmetric absmax (they commute out of both
# matmuls exactly as in int8).
# ---------------------------------------------------------------------------


class Int4KV(NamedTuple):
    """int4-packed KV cache: values (B, Hkv, N, d//2) int8 (two nibbles
    per byte) + per-token fp32 scales (B, Hkv, 8, N), layout-compatible
    with `QuantizedKV`'s scales."""

    k_q: jax.Array
    k_scale: jax.Array
    v_q: jax.Array
    v_scale: jax.Array

    @property
    def capacity(self) -> int:
        return self.k_q.shape[2]

    @property
    def head_dim(self) -> int:
        return 2 * self.k_q.shape[3]


def _quant_rows_int4(x):
    """Symmetric per-token absmax int4: (..., N, d) -> packed
    (..., N, d//2) int8 + (..., 8, N) replicated scales."""
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"head_dim {d} must be even for int4 packing")
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)  # (..., N)
    scale = jnp.where(amax == 0.0, 1.0, amax / 7.0)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    q = jnp.clip(q, -7, 7).astype(jnp.int8)
    lo = q[..., : d // 2]
    hi = q[..., d // 2:]
    packed = jnp.bitwise_or(
        jnp.bitwise_and(lo, 0xF), jnp.left_shift(hi, 4)
    ).astype(jnp.int8)
    scale_rep = jnp.broadcast_to(
        scale[..., None, :], (*scale.shape[:-1], 8, scale.shape[-1])
    )
    return packed, scale_rep


def _unpack_nibbles(packed):
    """int8 byte tile -> (lo, hi) bf16 nibble tiles of the same shape.

    Nibble extraction is float floor arithmetic, NOT integer shifts:
    Mosaic fails to legalize `arith.shli` on int8 vectors in-kernel
    (remote-compile HTTP 500, 'failed to legalize operation'), while
    convert/floor/fma all lower cleanly.  floor(p/16) IS the
    arithmetic right shift (rounds toward -inf), so `hi` comes out
    sign-extended; the low nibble is the remainder re-signed.  Values
    are small integers — exact in fp32.  The ONE home of this
    workaround: both int4 layouts (feature-dim and token-paired) build
    their unpacks from it."""
    p = packed.astype(jnp.float32)
    hi = jnp.floor(p * (1.0 / 16.0))
    lo = p - 16.0 * hi                       # [0, 15] unsigned nibble
    lo = jnp.where(lo >= 8.0, lo - 16.0, lo)  # two's-complement sign
    return lo.astype(jnp.bfloat16), hi.astype(jnp.bfloat16)


def _unpack_int4(packed):
    """(rows, d//2) int8 nibbles -> (rows, d) bf16 in natural feature
    order; halves concat along lanes (no element interleave)."""
    lo, hi = _unpack_nibbles(packed)
    return jnp.concatenate([lo, hi], axis=-1)


def quantize_kv_int4(k: jax.Array, v: jax.Array) -> Int4KV:
    """Quantize full (B, Hkv, N, d) K/V caches to the int4 cache format.

    MEASURED error budget (tests/test_quant.py, RESULTS.md round 5):
    ~4-8e-2 max abs output error on unit-normal inputs at d=64/128
    decode shapes — ~30x int8's ~2e-3, dominated by K's nibble
    granularity (absmax/7 per element) perturbing the logits.  That
    EXCEEDS the framework's ±0.02 harness contract: int4 is an OPT-IN
    bytes/quality trade (0.375x bf16 cache bytes at d=128 vs int8's
    0.625x — scales included) for workloads that tolerate it, NOT a
    drop-in.  Workloads needing contract-grade logits stay on
    `quantize_kv` (int8)."""
    k_q, k_s = _quant_rows_int4(k)
    v_q, v_s = _quant_rows_int4(v)
    return Int4KV(k_q, k_s, v_q, v_s)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_k", "interpret", "softcap", "window",
                     "sinks"),
)
def flash_decode_int4(
    q: jax.Array,          # (B, H, d)
    cache: Int4KV,
    lengths: jax.Array,    # (B,) int32 or scalar
    *,
    scale: float | None = None,
    block_k: int = 4096,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """softmax(q K[:len]^T * scale) V[:len] against an int4 cache.

    Same per-sequence band semantics as :func:`flash_decode_quantized`;
    streams 0.375x the bf16 cache bytes at d=128 (0.6x int8's, scales
    included).  Error budget:
    see `quantize_kv_int4`."""
    check_softcap(softcap)
    check_band(window, sinks)
    b, h, d = q.shape
    bk_, hkv, n, dk_half = cache.k_q.shape
    if bk_ != b or 2 * dk_half != d or cache.v_q.shape != (b, hkv, n, d // 2):
        raise ValueError(
            f"cache shapes inconsistent: Q{q.shape} K{cache.k_q.shape} "
            f"V{cache.v_q.shape}"
        )
    if cache.k_scale.shape != (b, hkv, 8, n) or \
            cache.v_scale.shape != (b, hkv, 8, n):
        raise ValueError(
            f"scale shapes {cache.k_scale.shape}/{cache.v_scale.shape} "
            f"!= {(b, hkv, 8, n)}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(jnp.bfloat16)
    qs = qs.reshape(b * hkv, group, d)
    group_pad = _ceil_to(group, 16)
    if group_pad != group:
        qs = jnp.pad(qs, ((0, 0), (0, group_pad - group), (0, 0)))

    block_k = _pick_block_k(n, block_k)
    kc = cache.k_q.reshape(b * hkv, n, d // 2)
    vc = cache.v_q.reshape(b * hkv, n, d // 2)
    ks = cache.k_scale.reshape(b * hkv, 8, n)
    vs = cache.v_scale.reshape(b * hkv, 8, n)

    def kv_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, banded_block_clamp(j, valid, block_k, window, sinks), 0)

    def scale_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, 0, banded_block_clamp(j, valid, block_k, window, sinks))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n // block_k),
        in_specs=[
            pl.BlockSpec((1, group_pad, d), lambda bh, j, lr: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d // 2), kv_index),
            pl.BlockSpec((1, 8, block_k), scale_index),
            pl.BlockSpec((1, block_k, d // 2), kv_index),
            pl.BlockSpec((1, 8, block_k), scale_index),
        ],
        out_specs=pl.BlockSpec((1, group_pad, d), lambda bh, j, lr: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group_pad, d), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            # ONE kernel body with the int8 path (unpack hook): the
            # masking/band logic exists in one place for both formats
            _decode_q_kernel, hkv=hkv, block_k=block_k,
            softcap2=None if softcap is None else softcap * _LOG2E,
            window=window, sinks=sinks, unpack=_unpack_int4,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, group_pad, d), jnp.bfloat16),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * n * d,
            bytes_accessed=kc.size + vc.size + (ks.size + vs.size) * 4
            + qs.size * 2,
            transcendentals=b * h * n,
        ),
        interpret=interpret,
    )(lens, qs, kc, ks, vc, vs)

    return out[:, :group].reshape(b, h, d)


# ---------------------------------------------------------------------------
# int4, token-paired packing (round 5, second attempt at the latency
# side).  The feature-dim packing above measured 0.748 ms vs int8's
# 0.445 at the bench decode shape: its (block_k, d/2=64) value tiles
# are HALF the native 128-lane width, so the value stream loses the
# full-width DMA efficiency the int8 kernel rides (RESULTS.md round 5).
# This layout packs two ADJACENT TOKENS per byte instead — byte row r
# holds token 2r in its low nibble and token 2r+1 in its high nibble,
# per feature — so value tiles stay (rows, d=128) full lane width and
# the unpack splits along SUBLANES (a concat on the major axis, no
# lane relayout).  The pairing stride is a constant 2, so the layout is
# independent of kernel tiling (no block_k coupling); scales ship
# pre-split even/odd (rows 0-7 / 8-15 of a 16-row replicated band) so
# the kernel's lane-concat of the two scale vectors matches the score
# tile's [even tokens | odd tokens] lane order with contiguous fetches.
# Quantization math (per-token symmetric absmax / 7) is IDENTICAL to
# the feature packing, so the error budget carries over unchanged.
# ---------------------------------------------------------------------------


class Int4TokKV(NamedTuple):
    """Token-paired int4 cache: values (B, Hkv, N//2, d) int8 (tokens
    2r/2r+1 in the low/high nibbles of byte row r) + per-token fp32
    scales (B, Hkv, 16, N//2) — sublanes 0-7 replicate the even-token
    scale, 8-15 the odd-token scale."""

    k_q: jax.Array
    k_scale: jax.Array
    v_q: jax.Array
    v_scale: jax.Array

    @property
    def capacity(self) -> int:
        return 2 * self.k_q.shape[2]

    @property
    def head_dim(self) -> int:
        return self.k_q.shape[3]


def _quant_rows_int4_tok(x):
    """Symmetric per-token absmax int4: (..., N, d) -> token-paired
    packed (..., N//2, d) int8 + (..., 16, N//2) even/odd scales."""
    n = x.shape[-2]
    if n % 2:
        raise ValueError(f"cache length {n} must be even for token pairing")
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)  # (..., N)
    scale = jnp.where(amax == 0.0, 1.0, amax / 7.0)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    q = jnp.clip(q, -7, 7).astype(jnp.int8)
    lo = q[..., 0::2, :]   # even tokens
    hi = q[..., 1::2, :]   # odd tokens
    packed = jnp.bitwise_or(
        jnp.bitwise_and(lo, 0xF), jnp.left_shift(hi, 4)
    ).astype(jnp.int8)
    se = jnp.broadcast_to(scale[..., None, 0::2],
                          (*scale.shape[:-1], 8, n // 2))
    so = jnp.broadcast_to(scale[..., None, 1::2],
                          (*scale.shape[:-1], 8, n // 2))
    return packed, jnp.concatenate([se, so], axis=-2)  # (..., 16, N//2)


def _unpack_int4_tok(packed):
    """(rows, d) token-paired int8 -> two (rows, d) bf16 value tiles
    (even tokens, odd tokens) in natural within-block order — here the
    two nibbles are two TOKEN rows sharing a byte row, so no lane
    concat is needed; the caller stacks the halves along sublanes.
    Nibble math lives in `_unpack_nibbles` (the Mosaic float-floor
    workaround's one home)."""
    return _unpack_nibbles(packed)


def _pick_block_tok(n: int, want: int) -> int:
    """Largest multiple of 256 that divides ``n`` and is <= ``want``
    rounded up to the next 256 (so an undersized ``want`` like 128
    resolves UP to the minimal valid block, 256, instead of failing).

    The token-paired kernel's packed block is ``block_tok // 2`` byte
    rows and must stay a multiple of the 128-row tile, so the token
    block steps by 256 — `decode._pick_block_k`'s 128-stepped search
    can land on an odd 128-multiple (e.g. n=4864, want=4096 -> 2432)
    that is a valid int8 block but not a valid packed one.  A
    256-multiple divisor always exists because `quantize_kv_int4_tok`
    requires n % 256 == 0."""
    if n % 256:
        raise ValueError(
            f"token-paired int4 cache capacity {n} must be a multiple "
            f"of 256"
        )
    bk = min(_ceil_to(want, 256), n)
    while n % bk:
        bk -= 256
    return bk


def quantize_kv_int4_tok(k: jax.Array, v: jax.Array) -> Int4TokKV:
    """Quantize full (B, Hkv, N, d) K/V caches to the token-paired int4
    format.  Same quantization math — and therefore the same measured
    ~4-8e-2 opt-in error budget — as :func:`quantize_kv_int4`; see that
    docstring for the contract discussion."""
    n = k.shape[-2]
    if n % 256:
        # the decode grid needs a 256-multiple token block dividing the
        # capacity; for n ≡ 128 (mod 256) no such block exists, so the
        # cache would be unusable by construction — fail at build time
        # with a capacity-phrased error, not at decode with a
        # block-size one
        raise ValueError(
            f"token-paired int4 needs a 256-multiple cache capacity, "
            f"got {n} (use the feature-dim layout for smaller caches)"
        )
    k_q, k_s = _quant_rows_int4_tok(k)
    v_q, v_s = _quant_rows_int4_tok(v)
    return Int4TokKV(k_q, k_s, v_q, v_s)


def _decode_tok4_kernel(
    lens_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
    acc_scr, m_scr, l_scr,
    *, hkv: int, block_tok: int, softcap2: float | None = None,
    window: int | None = None, sinks: int | None = None,
):
    """One (batch*kv-head, token-block) grid step against a
    token-paired int4 cache.  Mirrors `_decode_q_kernel`'s band logic
    through the same helpers (`banded_live`/`banded_keep`); the body
    differs because the unpack doubles the ROW count: a (bp, d) packed
    block becomes [even-token tile; odd-token tile] stacked along
    sublanes, the score tile's lanes run [even | odd], and the mask's
    column->token map is 2*lane (+1 for the odd half).

    This is the documented EXCEPTION to `_decode_q_kernel`'s one-body
    invariant (see its docstring): keep the two bodies' band/mask
    logic mirrored by hand; the cross-layout equality tests pin them.
    No ``chunk`` (speculative-verify) mode — neither int4 layout has
    one (speculative serving composes with the int8 cache,
    `flash_decode_quantized_chunk`; int4 remains an opt-in decode-only
    capacity/latency trade outside the ±0.02 contract)."""
    bh = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)
    valid = lens_ref[bh // hkv]
    bp = block_tok // 2
    kv_min = None
    if window is not None:
        kv_min = jnp.maximum(valid - window, 0)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = banded_live(j, valid, block_tok, window, sinks)

    @pl.when(live)
    def _tile():
        q = q_ref[0]                          # (group_pad, d), log2-prescaled
        k_lo, k_hi = _unpack_int4_tok(k_ref[0])
        kt = jnp.concatenate([k_lo, k_hi], axis=0)  # (block_tok, d)
        s = jax.lax.dot_general(
            q, kt, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                     # (group_pad, block_tok)
        ks = ks_ref[0]                        # (16, bp): even rows 0-7
        k_scale = jnp.concatenate(
            [jnp.max(ks[:8], axis=0, keepdims=True),
             jnp.max(ks[8:], axis=0, keepdims=True)], axis=-1
        )                                     # (1, block_tok), [even|odd]
        s = s * k_scale
        if softcap2 is not None:
            s = softcap2 * jnp.tanh(s / softcap2)
        lam = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        base = j * block_tok
        col = jnp.where(lam < bp,
                        base + 2 * lam,
                        base + 2 * (lam - bp) + 1)
        mask = col < valid
        if kv_min is not None:
            mask = jnp.logical_and(mask, banded_keep(col, kv_min, sinks))
        s = jnp.where(mask, s, NEG_INF)

        p, corr = _online_softmax_update(s, m_scr, l_scr, masked=True)
        vs = vs_ref[0]
        v_scale = jnp.concatenate(
            [jnp.max(vs[:8], axis=0, keepdims=True),
             jnp.max(vs[8:], axis=0, keepdims=True)], axis=-1
        )
        v_lo, v_hi = _unpack_int4_tok(v_ref[0])
        vt = jnp.concatenate([v_lo, v_hi], axis=0)  # (block_tok, d)
        pv = jax.lax.dot_general(
            (p * v_scale).astype(jnp.bfloat16),
            vt,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr + pv

    @pl.when(j == num_j - 1)
    def _finalize():
        l = jnp.max(l_scr[...], axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_k", "interpret", "softcap", "window",
                     "sinks"),
)
def flash_decode_int4_tok(
    q: jax.Array,          # (B, H, d)
    cache: Int4TokKV,
    lengths: jax.Array,    # (B,) int32 or scalar
    *,
    scale: float | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """softmax(q K[:len]^T * scale) V[:len] against a token-paired int4
    cache.  Same band semantics and error budget as
    :func:`flash_decode_int4`; ``block_k`` counts TOKENS (the packed
    block is ``block_k // 2`` byte rows at full d-lane width).

    Default block: **16384** tokens unwindowed — the measured optimum
    at the bench decode shape (b8/32q/4kv/32k, device clock: 0.565 /
    0.455 / 0.415 / 0.402 ms at 2048/4096/8192/16384; the unpack's VPU
    cost rewards fewer, larger steps once the stream is no longer
    DMA-bound) — and 4096 windowed, also measured: at w=4096+sinks on
    the same shape, 0.432 / 0.259 / 0.189 / 0.239 ms at
    1024/2048/4096/8192 (int8's same-window default: 0.171 — with the
    stream shrunk to the band, the unpack's VPU cost shows as a ~10%
    premium instead of a win; the capacity trade still stands)."""
    check_softcap(softcap)
    check_band(window, sinks)
    if block_k is None:
        block_k = 16384 if window is None else 4096
    b, h, d = q.shape
    bk_, hkv, n_half, dk_ = cache.k_q.shape
    n = 2 * n_half
    if bk_ != b or dk_ != d or cache.v_q.shape != (b, hkv, n_half, d):
        raise ValueError(
            f"cache shapes inconsistent: Q{q.shape} K{cache.k_q.shape} "
            f"V{cache.v_q.shape}"
        )
    if cache.k_scale.shape != (b, hkv, 16, n_half) or \
            cache.v_scale.shape != (b, hkv, 16, n_half):
        raise ValueError(
            f"scale shapes {cache.k_scale.shape}/{cache.v_scale.shape} "
            f"!= {(b, hkv, 16, n_half)}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    group = h // hkv

    lens = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(jnp.bfloat16)
    qs = qs.reshape(b * hkv, group, d)
    group_pad = _ceil_to(group, 16)
    if group_pad != group:
        qs = jnp.pad(qs, ((0, 0), (0, group_pad - group), (0, 0)))

    block_tok = _pick_block_tok(n, block_k)
    bp = block_tok // 2
    kc = cache.k_q.reshape(b * hkv, n_half, d)
    vc = cache.v_q.reshape(b * hkv, n_half, d)
    ks = cache.k_scale.reshape(b * hkv, 16, n_half)
    vs = cache.v_scale.reshape(b * hkv, 16, n_half)

    def kv_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, banded_block_clamp(j, valid, block_tok, window, sinks), 0)

    def scale_index(bh, j, lens_ref):
        valid = lens_ref[bh // hkv]
        return (bh, 0, banded_block_clamp(j, valid, block_tok, window, sinks))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n // block_tok),
        in_specs=[
            pl.BlockSpec((1, group_pad, d), lambda bh, j, lr: (bh, 0, 0)),
            pl.BlockSpec((1, bp, d), kv_index),
            pl.BlockSpec((1, 16, bp), scale_index),
            pl.BlockSpec((1, bp, d), kv_index),
            pl.BlockSpec((1, 16, bp), scale_index),
        ],
        out_specs=pl.BlockSpec((1, group_pad, d), lambda bh, j, lr: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group_pad, d), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
            pltpu.VMEM((group_pad, _STAT_LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_tok4_kernel, hkv=hkv, block_tok=block_tok,
            softcap2=None if softcap is None else softcap * _LOG2E,
            window=window, sinks=sinks,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * hkv, group_pad, d), jnp.bfloat16),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * n * d,
            bytes_accessed=kc.size + vc.size + (ks.size + vs.size) * 4
            + qs.size * 2,
            transcendentals=b * h * n,
        ),
        interpret=interpret,
    )(lens, qs, kc, ks, vc, vs)

    return out[:, :group].reshape(b, h, d)
