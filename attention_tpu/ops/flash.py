"""Fused Pallas flash-attention kernel for TPU.

This is the TPU-native rebuild of the reference's entire AVX-512 kernel
stack (`attention-mpi.c:103-189`):

  * ``dot_avx512`` (QK^T inner loop)      → tiled `jax.lax.dot_general` on
    the 128x128 MXU;
  * ``axpy_avx512`` (softmax-weighted V)  → the P·V tile matmul, also MXU;
  * ``memset_zero_scale``                 → vectorized scratch init /
    rescale on the VPU;
  * ``online_softmax_attention`` (running rmax/rsum, rescale by
    exp(old-new), `attention-mpi.c:168-189`) → the in-kernel online
    softmax carried in VMEM scratch across the KV grid dimension;
  * ``_mm_prefetch`` of the next K/V rows → Pallas' automatic grid
    double-buffering of the next K/V block's HBM→VMEM DMA;
  * ``cvt_d2f_avx512`` mixed precision    → bf16/fp32 inputs with fp32
    accumulation (``preferred_element_type``).

Two entry points share one kernel:

  * :func:`flash_attention` — normalized output, the single-chip fused op.
  * :func:`flash_attention_partials` — returns ``(out_unnorm, row_max,
    row_sumexp)`` per KV shard, the exact contract of the reference's
    local pass (each rank's (contrib, lmax, lsum), `attention-mpi.c:333-338`)
    that the distributed two-phase normalization
    (`attention_tpu.parallel`) merges across devices.
"""

from __future__ import annotations

import functools
import logging
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu import obs

_logger = logging.getLogger("attention_tpu.ops.flash")

# Op-dispatch telemetry (attention_tpu.obs, off by default).  Call
# counts tick per host-side dispatch; a call inside an enclosing jit
# trace ticks once per TRACE, not per execution — Python cannot see
# compiled re-executions.  `ops.flash.lowered` ticks at trace time in
# `_flash_call` and records the bound->online static dispatch choice.
_FLASH_CALLS = obs.counter("ops.flash.calls",
                           "flash_attention dispatches by shape bucket")
_FLASH_LOWERED = obs.counter(
    "ops.flash.lowered",
    "kernel lowerings by requested/resolved max mode")

NEG_INF = float("-inf")
_STAT_LANES = 128  # stats are carried lane-replicated: min f32 tile is (8, 128)
_LOG2E = 1.4426950408889634  # log2(e)
_LN2 = 0.6931471805599453  # 1/log2(e)

# Bound mode's runtime safety threshold, in log2 units.  The bound kernel
# computes p = exp2(s - b) with b >= the true row max; every probability
# is scaled by 2^-(overshoot).  fp32 normals reach 2^-126, so overshoot
# past ~126 silently underflows ALL of a row's probabilities -> l = 0 ->
# the div-guard returns zeros.  96 keeps the per-row max probability a
# normal float with 30 log2 units of margin, and entries within 2^-26 of
# it exactly representable (bf16 inputs carry ~2^-8 anyway).  Calls whose
# estimated overshoot exceeds this self-demote to the online kernel
# (`_bound_overshoot_estimate`) — the analog of the reference *buying*
# its fp32 headroom deliberately (attention-mpi.c:224-225) rather than
# assuming it.
SAFE_OVERSHOOT_LOG2 = 96.0

# Perf-triage ONLY (see the dispatch in `_flash_call`): monkeypatch to
# True to time the bound kernel without its guard/cond.  Deliberately a
# code-settable module global, not an env var — correctness bypasses
# must not ride process environments into CI, and jit caches freeze the
# value at first trace anyway.
_UNSAFE_SKIP_GUARD = False

# Static small-shape resolution of max_mode="bound" -> online (see the
# dispatch in `_flash_call`): below this many score elements
# (h * m_pad * n_pad, halved for causal) the overshoot guard's flat
# cond cost exceeds bound mode's VPU saving.  Measured round 5 between
# causal 4k (8.4M elems, online wins by 35%) and causal 8k (33.6M,
# bound wins by 21%) — 24M sits with margin on both sides.
_BOUND_MIN_SCORE_ELEMS = 24 * 2**20


def _compiler_params_cls():
    """The pallas TPU compiler-params class under either of its
    spellings (``CompilerParams`` in newer pallas, ``TPUCompilerParams``
    in older), or None when neither exists."""
    return (getattr(pltpu, "CompilerParams", None)
            or getattr(pltpu, "TPUCompilerParams", None))


def _compiler_params(semantics, vmem_limit_bytes=None):
    """CompilerParams with dimension semantics, tolerant of API spelling
    drift across pallas versions — both the CLASS name
    (CompilerParams/TPUCompilerParams) and its kwargs (shared by the
    forward and backward kernels).  ``vmem_limit_bytes`` raises
    Mosaic's scoped-VMEM budget — the fused backward kernel's
    VMEM-resident (m_pad, d) fp32 dQ block legitimately exceeds the
    default budget."""
    cls = _compiler_params_cls()
    if cls is None:
        return None
    kw = {"dimension_semantics": semantics}
    if vmem_limit_bytes is not None:
        kw["vmem_limit_bytes"] = vmem_limit_bytes
    try:
        return cls(**kw)
    except TypeError:  # older/newer param spelling
        try:
            return cls(dimension_semantics=semantics)
        except TypeError:
            return None


class BlockSizes(NamedTuple):
    """Tile sizes for the flash kernel grid.

    Defaults target v5e: 128-aligned so QK^T and P·V tiles map directly to
    the MXU, sized so q/k/v/acc blocks fit comfortably in ~16 MB VMEM with
    double buffering (the compiler pipelines the next K/V block while the
    current one computes — the `_mm_prefetch` analog).  256x1024 measured
    best on the real chip at seq=32k, d=128: 88.7% of peak matmul FLOPs
    vs 73.6% for 512x512 (scripts/kernel_sweep.py).
    """

    block_q: int = 256
    block_k: int = 1024

    @classmethod
    def for_shape(cls, heads: int, m: int, d: int,
                  window: int | None = None,
                  returns_stats: bool = False,
                  causal: bool = False,
                  dtype=None) -> "BlockSizes":
        """Per-shape defaults (callers may always override): the tuning
        tables first (user cache, then the shipped table — both keyed
        by device kind, so CPU/interpret runs with no cache entries
        resolve exactly as before), then the measured heuristic
        (:meth:`heuristic_for_shape`).  ``python -m attention_tpu.cli
        tune`` records fresh per-device optima into the user cache.
        """
        tuned = _tuned_flash_tiles(heads, m, d, window=window,
                                   returns_stats=returns_stats,
                                   causal=causal, dtype=dtype)
        if tuned is not None:
            return cls(*tuned)
        return cls(*cls.heuristic_for_shape(m, d, window=window,
                                            returns_stats=returns_stats,
                                            causal=causal))

    @classmethod
    def heuristic_for_shape(cls, m: int, d: int, *,
                            window: int | None = None,
                            returns_stats: bool = False,
                            causal: bool = False,
                            big_tiles: bool | None = None
                            ) -> tuple[int, int]:
        """The measured heuristic defaults (the tuner's final fallback;
        ``scripts/make_shipped_table.py`` seeds the shipped table from
        this with ``big_tiles=True`` — the measured-generation value —
        while ``None`` probes the local device).

        Round 4: raising the kernel's scoped-VMEM budget (it sat at
        Mosaic's ~16 MB default, which rejected every tile bigger than
        the then-measured optima — the sweep space was cut off exactly
        at the boundary the defaults sat on) unlocks a universal
        **4096x2048** for every unwindowed d<=128 shape with m >= 8192,
        stats outputs included.  Device clock: single-head 8k 185.0 us
        (0.943 vs 0.925 for the old 2048x1024), 32k 2.867 ms (0.973 vs
        0.951), 131k 45.39 ms (0.984 vs 0.959), GQA 32q/4kv@16k
        23.55 ms (0.948 vs 0.918 for the old 1024x2048), partials 32k
        2.967 ms (0.941 vs 0.888 for the old capped 1024x1024 — the
        cap existed only because of the old VMEM budget).
        Windowed long sequences keep the compact **512x512** tile — the
        band covers ceil((window-1+block_q)/block_k)+1 KV blocks, so
        smaller square tiles waste less of the band on masked columns:
        at seq=32k (device clock) w=1024 runs 227 us vs 329 for the
        general default, w=4096 575 vs 718, w=256 166 vs 153 for
        256x512 (within a whisker of the best).
        """
        if d <= 128 and m >= 8192:
            if window is not None:
                return (512, 512)
            if big_tiles is None:
                big_tiles = _vmem_limit_supported() and _big_tile_device()
            if not big_tiles:
                # without the raised budget (old pallas) or enough
                # physical VMEM (v2/v3 cores ~16 MB accept the kwarg
                # but cannot honor it) the big tiles cannot compile:
                # keep the round-3 defaults that fit ~16 MB
                return (1024, 1024) if returns_stats else (2048, 1024)
            # padding-aware: _flash_call pads m to a block_q multiple,
            # so a 4096-row tile on e.g. m=10240 would compute +20%
            # garbage rows; 2048 bounds the padding at 2047 rows
            bq = 4096 if m % 4096 == 0 else 2048
            if causal:
                # the diagonal wastes more of a taller tile: 2048x2048
                # measured 1.580 ms at causal 32k vs 1.643 for the
                # non-causal optimum (and 1.618 for the old 2048x1024)
                bq = min(bq, 2048)
            return (bq, 2048 if m % 2048 == 0 else 1024)
        return (cls._field_defaults["block_q"],
                cls._field_defaults["block_k"])


def _tuned_flash_tiles(heads, m, d, *, window, returns_stats, causal,
                       dtype):
    """Tuning-table tiles for the forward kernel, or None (heuristic).

    Floor-pow2 bucketing means an entry measured at one shape serves a
    range; the entry's tiles are re-bounded to THIS call's padding the
    same way the heuristic bounds its own (block_q that doesn't divide
    m caps at 2048 / block_k at 1024 — `_flash_call` pads m to a
    block_q multiple, and an unbounded tile on an unaligned m computes
    garbage rows).
    """
    try:
        from attention_tpu.tuning.lookup import key_fields, lookup

        entry = lookup(
            "flash_fwd", dtype=dtype,
            **key_fields("flash_fwd", heads=heads, seq=m, dim=d,
                         causal=causal, window=window,
                         stats=returns_stats),
        )
    except Exception:  # noqa: BLE001 - tuning must never break dispatch
        return None
    if entry is None:
        return None
    try:
        bq, bk = int(entry["block_q"]), int(entry["block_k"])
    except (KeyError, TypeError, ValueError):
        return None
    if bq % 128 or bk % 128 or bq <= 0 or bk <= 0:
        return None
    bq = min(bq, _ceil_to(m, 128))
    bk = min(bk, _ceil_to(m, 128))
    if m % bq:
        bq = min(bq, 2048)
    if m % bk:
        bk = min(bk, 1024)
    return bq, bk


def _tuned_max_mode(kernel: str, *, dtype=None, default: str = "online",
                    allowed=None, **kf_kwargs) -> str:
    """Tuning-table rescaling-math pick for ``max_mode="auto"`` calls,
    or ``default`` on a miss/invalid entry.

    Shared by the flash forward, decode, and ragged dispatchers: each
    passes its own family name plus `key_fields` kwargs (and its own
    ``allowed`` set — the decode-side kernels cannot lower "bound",
    which needs the forward kernel's key-norm prefetch).  The fallback
    is the online oracle — NOT bound — so an empty-cache CPU run of an
    "auto" call lowers exactly the kernel the plain default would.
    """
    try:
        from attention_tpu.tuning.lookup import key_fields, lookup

        entry = lookup(kernel, dtype=dtype,
                       **key_fields(kernel, **kf_kwargs))
    except Exception:  # noqa: BLE001 - tuning must never break dispatch
        return default
    if entry is None:
        return default
    mode = entry.get("max_mode")
    return mode if mode in (allowed or MAX_MODES) else default


def _vmem_limit_supported() -> bool:
    """Whether this pallas accepts ``vmem_limit_bytes`` — the big-tile
    forward default and the fused backward both NEED the raised budget;
    without support the defaults must stay inside Mosaic's ~16 MB."""
    cls = _compiler_params_cls()
    if cls is None:
        return False
    try:
        cls(dimension_semantics=("parallel",), vmem_limit_bytes=2**20)
        return True
    except TypeError:
        return False


@functools.cache
def _big_tile_device() -> bool:
    """Whether the default device's physical VMEM can hold the big-tile
    defaults (~110 MB scoped budget).  `_vmem_limit_supported` only
    proves the API accepts the kwarg; a v2/v3 core (~16 MB VMEM) would
    accept it and then fail to compile, so gate on the generation too.
    Non-TPU backends (pallas interpret mode) have no VMEM to exhaust."""
    try:
        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001 - no backend at all
        return False
    if dev.platform != "tpu":
        return True
    kind = getattr(dev, "device_kind", "").lower()
    return any(gen in kind for gen in ("v4", "v5", "v6", "v7"))


def _ceil_to(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _flash_kernel(
    offsets_ref,
    knmax_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,
    n_true: int,
    block_k: int,
    causal: bool,
    block_q: int,
    normalize: bool,
    out_dtype,
    dynamic_valid: bool,
    segmented: bool,
    window: int | None,
    n_true_blocks: int,
    softcap2: float | None = None,
    sinks: int | None = None,
    sink_blocks: int = 0,
    variant: str = "online",
):
    """One (head, q-block, kv-block) grid step of online-softmax attention.

    ``offsets_ref`` holds (q_offset, kv_offset, kv_valid) as dynamic SMEM
    scalars: the global positions of this call's Q/KV rows (causal masking
    stays correct when the caller holds only a shard — ring attention
    rotates KV shards and computes the rotating offset from its device
    index) and the number of valid local KV rows (< n when the caller's
    shard includes padding from an indivisible global sequence).
    ``window`` (static) keeps only the last ``window`` positions per row
    (sliding-window attention; requires causal).
    ``variant`` picks the rescaling math (all variants compute the same
    softmax; they differ in which per-tile VPU work the recurrence
    carries — see `_softmax_variant_update`):

      * ``"online"`` — the classic running rmax/rsum recurrence.
      * ``"bound"`` (the VFA idea, PAPERS.md: global-max precompute) —
        replaces the online max recurrence with a per-row upper bound on
        the scores, computed in-kernel at the first KV step from the
        resident Q block and the prefetched per-KV-head max key norm
        (``knmax_ref``, Cauchy-Schwarz: |q·k| <= ||q||·max||k||):
        softmax is invariant to which max is subtracted, so using a
        bound instead of the true running max gives the same normalized
        output and lse while deleting the row-max reduce, the corr exp2,
        the accumulator rescale and the m-scratch traffic from the
        serial VPU chain.  ``l`` then accumulates per-lane and reduces
        once at finalize.  The m scratch holds the bound (written once,
        read per tile) instead of the running max.
      * ``"flashd"`` (FLASH-D, PAPERS.md) — keeps the accumulator
        NORMALIZED throughout: the division is folded into the tile
        update, the m scratch carries the running log-sum-exp, and the
        finalize has no ``l``-division epilogue.
      * ``"amla"`` (AMLA, PAPERS.md) — quantizes the running max to
        integers so every rescale factor is a power of two, applied as
        an integer add on the fp32 exponent field instead of a
        multiply.

    ``rest`` = ([q_seg, kv_seg,] o_ref, m_out, l_out, acc, m, l).
    """
    if segmented:
        q_seg_ref, kv_seg_ref, *rest = rest
    else:
        q_seg_ref = kv_seg_ref = None
    o_ref, m_out_ref, l_out_ref, acc_scr, m_scr, l_scr = rest
    # program_id is read at the kernel top level: interpret mode on CPU
    # substitutes grid indices only there, and the values are
    # loop-invariant anyway.
    h_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    jb = pl.program_id(2)
    if window is None:
        kv_idx = jb
    else:
        # Banded grid: the j dimension covers only the window band, and
        # the absolute KV block index is band-start + j.  A full-width
        # grid with per-step skip guards is NOT free — each skipped step
        # still pays un-overlapped DMA latency (~10 us measured), which
        # made a w=1024 window 5x SLOWER than full causal at seq=32k.
        # with sinks, the first sink_blocks grid steps visit blocks
        # [0, sink_blocks) and the band starts no earlier than that
        # (no block is ever visited twice)
        base = jnp.maximum(
            (q_idx * block_q + offsets_ref[0] - offsets_ref[1]
             - (window - 1)) // block_k,
            sink_blocks,
        )
        if sink_blocks:
            kv_idx = jnp.where(jb < sink_blocks, jb,
                               base + jb - sink_blocks)
        else:
            kv_idx = base + jb

    @pl.when(jb == 0)
    def _init():
        if variant == "bound":
            # Cauchy-Schwarz bound from the resident (pre-scaled) Q
            # block and this head's prefetched max key norm; softcap
            # tightens it (|cap·tanh(s/cap)| <= min(|s|, cap)).
            q0 = q_ref[0].astype(jnp.float32)
            qn = jnp.sqrt(jnp.sum(q0 * q0, axis=-1, keepdims=True))
            b = qn * knmax_ref[h_idx]
            if softcap2 is not None:
                b = jnp.minimum(b, softcap2)
            m_scr[...] = jnp.broadcast_to(b, m_scr.shape)
        else:
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Skip tiles that masking zeroes entirely: under causal, KV blocks
    # strictly above the diagonal (first column already past the last
    # row); under dynamic kv_valid, blocks wholly past the valid prefix.
    # The running (m, l, acc) state is untouched for skipped tiles —
    # exactly what computing them would produce — so init/finalize stay
    # outside the guard.  This halves causal FLOPs (the score rectangle
    # becomes a triangle).
    compute_tile = True
    if causal:
        compute_tile = jnp.logical_and(
            compute_tile,
            kv_idx * block_k + offsets_ref[1]
            <= q_idx * block_q + block_q - 1 + offsets_ref[0],
        )
    if window is not None:
        # the band's top edge can run past the last real KV block (the
        # index map clips the DMA; skip the compute)
        compute_tile = jnp.logical_and(
            compute_tile, kv_idx < n_true_blocks
        )
    if dynamic_valid:
        compute_tile = jnp.logical_and(
            compute_tile, kv_idx * block_k < offsets_ref[2]
        )

    tile_kwargs = dict(
        valid=offsets_ref[2] if dynamic_valid else None,
        q_offset=offsets_ref[0],
        kv_offset=offsets_ref[1],
        kv_idx=kv_idx, q_idx=q_idx,
        n_true=n_true, block_k=block_k,
        block_q=block_q,
        q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref,
        softcap2=softcap2,
        variant=variant,
    )
    # Round-5 measured NEGATIVE result: splitting the body into an
    # interior fast path (mask chain statically compiled out for tiles
    # fully inside the causal triangle / window band) vs a diagonal
    # path — two @pl.when bodies on complementary predicates — ran
    # SLOWER on the real chip (causal 32k 1.72 ms vs 1.65 single-body
    # same-session; windowed w=1024 0.36 vs 0.21): Mosaic schedules
    # the dual-body step worse than it pays for the skipped VPU mask
    # chain.  Single masked body kept (the reference's aligned-vs-tail
    # split, attention-mpi.c:107-119, does not transplant here).
    @pl.when(compute_tile)
    def _compute():
        _flash_tile(q_ref, k_ref, v_ref, acc_scr, m_scr, l_scr,
                    causal=causal, window=window, sinks=sinks,
                    **tile_kwargs)

    @pl.when(jb == pl.num_programs(2) - 1)
    def _finalize():
        acc = acc_scr[...]
        if variant == "bound":
            # l accumulated per lane: one cross-lane reduce, here only
            l = jnp.sum(l_scr[...], axis=-1, keepdims=True)
        else:
            l = jnp.max(l_scr[...], axis=-1, keepdims=True)
        if normalize and variant != "flashd":
            # 1/gsum normalization with the divide-by-zero guard the
            # reference applies (attention-mpi.c:358-362).
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc / l_safe).astype(out_dtype)
        else:
            # flashd carries the accumulator normalized — the division
            # already happened inside the tile updates, so the epilogue
            # is a plain cast either way.
            o_ref[0] = acc.astype(out_dtype)
        if m_out_ref is not None:
            # Stats leave the kernel in the natural-log domain (the
            # distributed pmax/psum merge computes exp(lmax - gmax)).
            # In bound mode m_scr holds the bound — any value >= the
            # true row max yields the same merge and lse; in flashd it
            # holds the running log-sum-exp with l == 1 (the merge
            # identity sum_i out_i*exp(lse_i-gmax) / sum_i exp(lse_i-
            # gmax) is the standard two-phase combine); in amla the
            # integer-quantized max — still the actually-subtracted max.
            m_out_ref[0] = m_scr[...] * _LN2
            if variant == "bound":
                l_out_ref[0] = jnp.broadcast_to(l, l_out_ref[0].shape)
            else:
                l_out_ref[0] = l_scr[...]


def banded_keep(col, kv_min, sinks):
    """Decode-side band keep-mask: columns inside [kv_min, ...) or in the
    pinned first ``sinks`` rows.  One definition shared by `_flash_tile`
    and the int8 decode kernel so the band semantics cannot diverge."""
    keep = col >= kv_min
    if sinks is not None:
        keep = jnp.logical_or(keep, col < sinks)
    return keep


def _flash_tile(
    q_ref, k_ref, v_ref, acc_scr, m_scr, l_scr,
    *, valid, q_offset, kv_offset, kv_idx, q_idx, n_true, block_k, causal,
    block_q, q_seg_ref=None, kv_seg_ref=None, window=None, softcap2=None,
    sinks=None, kv_min=None, variant="online", pos_mod=None,
):
    """The per-tile online-softmax update (body of `_flash_kernel`; also
    the tile body of the decode kernel, `ops/decode.py`).  ``valid`` is a
    traced count of valid KV rows, or None when all ``n_true`` rows are
    valid (static masking only).  ``q_seg_ref``/``kv_seg_ref`` are
    segment-id blocks (lane-replicated (block_q, 128) / sublane-
    replicated (8, block_k) — see `segment_masks`); scores cross segment
    boundaries are masked.  ``pos_mod`` (static): the tile's rows pack
    several independent row streams (GQA group heads, or a speculative
    verify chunk replicated per head) — the row's SEQUENCE position is
    ``q_offset + row % pos_mod`` instead of ``q_offset + row``, so
    causal/window masks repeat every ``pos_mod`` rows."""
    dynamic_valid = valid is not None
    segmented = q_seg_ref is not None
    banded = kv_min is not None  # decode-side window: cols in
    # [kv_min, valid) plus the pinned first `sinks` positions

    # Q arrives pre-scaled by scale*log2(e) (`_flash_call`), so `s` is the
    # scores in the log2 domain: exp(s_nat - m_nat) == exp2(s - m).  This
    # removes the per-score scale multiply AND turns every exp into a raw
    # exp2 (TPU's native transcendental) — the kernel is VPU-bound, so
    # each elementwise op on the (block_q, block_k) tile is ~10% of step
    # time.  Stats are converted back to the natural domain at finalize.
    q = q_ref[0]  # (block_q, d)
    k = k_ref[0]  # (block_k, d)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (block_q, block_k), log2-domain
    if softcap2 is not None:
        # logit soft-capping (Gemma-2 style): cap * tanh(s / cap),
        # applied before masking; softcap2 is the cap in log2 units
        # (cap * log2(e)) since s is log2-domain
        s = softcap2 * jnp.tanh(s / softcap2)

    needs_tail_mask = n_true % block_k != 0
    masked = needs_tail_mask or causal or dynamic_valid or segmented or banded
    if masked:
        col = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1
        )
        mask = col < (valid if dynamic_valid else n_true)
        if causal:
            row = jax.lax.broadcasted_iota(
                jnp.int32, s.shape, dimension=0
            )
            if pos_mod is not None:
                row = jax.lax.rem(row, pos_mod)
            row = q_idx * block_q + row
            mask = jnp.logical_and(
                mask, col + kv_offset <= row + q_offset
            )
            if window is not None:
                # keep the last `window` positions per row, plus the
                # pinned first `sinks` positions (StreamingLLM)
                win = col + kv_offset >= row + q_offset - (window - 1)
                if sinks is not None:
                    win = jnp.logical_or(win, col + kv_offset < sinks)
                mask = jnp.logical_and(mask, win)
        if banded:
            mask = jnp.logical_and(mask, banded_keep(col, kv_min, sinks))
        if segmented:
            # (block_q, 1) vs (1, block_k): all lanes/sublanes of the
            # replicated id blocks are equal, so max() is just a reshape.
            q_ids = jnp.max(q_seg_ref[...], axis=-1, keepdims=True)
            kv_ids = jnp.max(kv_seg_ref[...], axis=0, keepdims=True)
            mask = jnp.logical_and(mask, q_ids == kv_ids)
        s = jnp.where(mask, s, NEG_INF)

    if variant == "bound":
        # Bound mode (VFA): the per-row score max is replaced by the
        # upper bound `_init` stored in m_scr, so there is no running
        # max, no corr, no accumulator rescale — the whole tile update
        # is one exp2, one per-lane partial sum and the P·V matmul.
        # Masked entries are -inf ⇒ exp2(-inf - b) = 0 (bound finite).
        b_col = jnp.max(m_scr[...], axis=-1, keepdims=True)
        p = jnp.exp2(s - b_col)
        # per-lane partial sums via lane-aligned slices (a reshape-based
        # (bq, bk/128, 128) reduce forces a Mosaic relayout — measured
        # 1.6x slower and +10MB scoped VMEM at 32k)
        lane_sum = p[:, :_STAT_LANES]
        for g in range(1, block_k // _STAT_LANES):
            lane_sum = lane_sum + p[:, g * _STAT_LANES:(g + 1) * _STAT_LANES]
        l_scr[...] += lane_sum
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] += pv
        return

    p, update_acc = _softmax_variant_update(s, m_scr, l_scr,
                                            variant=variant, masked=masked)

    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype),
        v_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[...] = update_acc(acc_scr[...], pv)


def _online_softmax_update(s, m_scr, l_scr, *, masked):
    """The rmax/rsum recurrence of `online_softmax_attention`
    (attention-mpi.c:175-182), shared by the forward, decode, and
    quantized-decode kernels.  Updates the lane-replicated (rows, 128)
    m/l VMEM scratches in place from log2-domain scores ``s`` and
    returns ``(p, corr)`` — the probability tile and the accumulator
    rescale factor exp(old_max - new_max) (attention-mpi.c:179-181).
    Stats are reduced back to (rows, 1) columns instead of lane-slicing.
    """
    m_prev = jnp.max(m_scr[...], axis=-1, keepdims=True)  # (rows, 1)
    l_prev = jnp.max(l_scr[...], axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    if masked:
        # the where-guards keep fully masked blocks/rows from producing
        # NaN via exp2(-inf - -inf)
        corr = jnp.where(m_prev == NEG_INF, 0.0, jnp.exp2(m_prev - m_next))
        p = jnp.where(m_next == NEG_INF, 0.0, jnp.exp2(s - m_next))
    else:
        # Unmasked: m_next is finite (a real row max), so exp2(-inf - m)
        # underflows to 0 on its own — skip the two per-element selects.
        corr = jnp.exp2(m_prev - m_next)
        p = jnp.exp2(s - m_next)
    l_next = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)
    return p, corr


#: valid per-tile rescaling-math variants (see `_softmax_variant_update`);
#: ``"auto"`` additionally resolves through the tuning tables at dispatch.
MAX_MODES = ("online", "bound", "flashd", "amla")


def _softmax_variant_update(s, m_scr, l_scr, *, variant, masked):
    """Per-tile softmax-recurrence dispatch shared by the flash forward,
    decode, and ragged kernel bodies (which differ only in how they index
    Q/K/V around this update).

    Returns ``(p, update_acc)``: the probability tile to feed the P·V
    matmul and a closure ``update_acc(acc, pv) -> new_acc`` folding the
    variant's rescale math into the accumulator update.  ``"bound"`` is
    NOT dispatched here — it needs the prefetched key-norm bound and has
    its own tile body in `_flash_tile`.
    """
    if variant == "flashd":
        return _flashd_update(s, m_scr, l_scr, masked=masked)
    if variant == "amla":
        return _amla_update(s, m_scr, l_scr, masked=masked)
    p, corr = _online_softmax_update(s, m_scr, l_scr, masked=masked)
    return p, lambda acc, pv: acc * corr + pv


def _flashd_update(s, m_scr, l_scr, *, masked):
    """FLASH-D (PAPERS.md, arXiv:2505.14201): hidden softmax division.

    The accumulator is kept NORMALIZED at every step — the tile update
    divides the probability tile and the carried accumulator by the
    running denominator as it goes, so there is no per-block rescale
    multiply against the old un-normalized accumulator and no final
    ``l``-division epilogue.  The m scratch carries the running
    log-sum-exp ``mu = log2(sum_j exp2(s_j))`` instead of the running
    max (itself the nonlinear part of the paper's recurrence); the l
    scratch is pinned to 1 so the stats contract ``out_unnorm = out *
    l * exp(m)/exp(m)`` holds with ``l == 1`` and ``m == lse`` — the
    distributed two-phase merge is unchanged.
    """
    mu_prev = jnp.max(m_scr[...], axis=-1, keepdims=True)  # running lse
    b = jnp.maximum(mu_prev, jnp.max(s, axis=-1, keepdims=True))
    if masked:
        # guards: a fully-masked tile on an empty history has b = -inf
        p = jnp.where(b == NEG_INF, 0.0, jnp.exp2(s - b))
        a = jnp.where(mu_prev == NEG_INF, 0.0, jnp.exp2(mu_prev - b))
    else:
        # unmasked: b is a real (finite) row max, exp2(-inf - b)
        # underflows to the right 0 on its own
        p = jnp.exp2(s - b)
        a = jnp.exp2(mu_prev - b)
    # t = exp2(-b) * (sum of ALL exponentials so far): the new
    # denominator, pre-divided out of both p and the carried acc
    t = a + jnp.sum(p, axis=-1, keepdims=True)
    rt = jnp.where(t == 0.0, 0.0, 1.0 / t)
    # mu_new = log2(sum_j exp2(s_j)); t == 0 only when b == -inf, and
    # -inf + log2(0) = -inf keeps the empty-row sentinel exact
    mu_new = b + jnp.log2(t)
    m_scr[...] = jnp.broadcast_to(mu_new, m_scr.shape)
    l_scr[...] = jnp.ones_like(l_scr)
    corr = a * rt
    return p * rt, lambda acc, pv: acc * corr + pv


def _amla_update(s, m_scr, l_scr, *, masked):
    """AMLA (PAPERS.md, arXiv:2509.25224): rescale multiplies become
    exponent-field integer adds.

    The running max is quantized UP to an integer (scores are already
    log2-domain from the Q prescale, so integer units = powers of two):
    every rescale factor ``exp2(m_prev - m_next)`` then has an exact
    fp32 representation with an all-zero mantissa delta, and multiplying
    the accumulator / denominator by it reduces to adding the (negative)
    integer ``m_prev - m_next`` to their exponent fields
    (`_exponent_add`) — no VPU multiply, bit-exact.  Ceiling (not floor)
    keeps ``s - m_next <= 0`` so ``p <= 1`` retains bound-mode's
    overflow-free property with at most one extra log2 unit of
    underflow headroom spent.
    """
    m_prev = jnp.max(m_scr[...], axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, jnp.ceil(jnp.max(s, axis=-1,
                                                  keepdims=True)))
    if masked:
        p = jnp.where(m_next == NEG_INF, 0.0, jnp.exp2(s - m_next))
    else:
        p = jnp.exp2(s - m_next)
    # diff <= 0 and integer-valued (both maxes are ceil-quantized);
    # fully-masked history (m_prev == -inf) rescales nothing: diff = 0
    diff = jnp.where(m_prev == NEG_INF, 0.0,
                     m_prev - m_next).astype(jnp.int32)
    l_prev = jnp.max(l_scr[...], axis=-1, keepdims=True)
    l_next = _exponent_add(l_prev, diff) + jnp.sum(p, axis=-1,
                                                   keepdims=True)
    m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)
    return p, lambda acc, pv: _exponent_add(acc, diff) + pv


def _exponent_add(x, e):
    """``x * 2**e`` as an integer add on the fp32 exponent field.

    ``e`` is a non-positive int32 (broadcastable against ``x``).  Exact
    for every normal fp32 input; zeros pass through and results whose
    biased exponent would leave the normal range flush to zero (the
    rescale factor is < 2^-126 there — the product is below any budget
    in the ledger).  The sign bit is untouched: with the result exponent
    in [1, 254] the add never borrows past bit 30.
    """
    bits = jax.lax.bitcast_convert_type(x, jnp.int32)
    exp = jax.lax.shift_right_logical(bits, 23) & 0xFF
    shifted = jax.lax.bitcast_convert_type(bits + (e << 23), jnp.float32)
    return jnp.where((x == 0.0) | (exp + e <= 0), 0.0, shifted)


def _bound_overshoot_estimate(q, k, knmax, offsets, *, m, n, group,
                              causal, window, sinks, softcap2,
                              q_segment_ids, kv_segment_ids,
                              static_diag=False):
    """Upper bound on bound-mode's per-row overshoot (log2 units).

    Bound mode subtracts the Cauchy-Schwarz row bound ``b`` instead of
    the true row max ``max_s``; correctness only needs the overshoot
    ``b - max_s`` to stay inside fp32 exp2 range (SAFE_OVERSHOOT_LOG2).
    ``max_s`` is unknown without running QK^T, but any single column
    certified attended for the row gives ``s_ref <= max_s``, hence
    ``b - s_ref >= b - max_s`` — a cheap O(m*d) overestimate computed
    from one gathered K row per query row.  Reference columns:

      * non-causal: column 0 (attended whenever any column is valid);
      * causal: the diagonal clipped into the valid prefix (column 0 is
        also always attended once the diagonal is local, but the
        diagonal score is far tighter for real models);
      * windowed: the clipped diagonal when it lies in the band, else
        sink column 0 when sinks exist;
      * rows that attend NO columns are excluded — for them bound-mode
        underflow produces exactly the correct zeros.

    Segmented calls certify the reference column only when it shares
    the row's segment; otherwise the row reports +inf (conservative
    demotion).  ``q`` arrives pre-scaled into the log2 domain, so the
    returned value is directly comparable to SAFE_OVERSHOOT_LOG2.

    ``static_diag``: the caller statically knows row i's reference IS
    kv row i (plain causal self-attention: no offsets, no kv_valid,
    m == n) — the diagonal reference becomes a fused elementwise
    q*k pass with NO gather and no exclusions (the diagonal is always
    attended and always inside any window).  This keeps the guard at
    ~1% of a causal 32k forward; the general gather path is reserved
    for sharded/offset callers.
    """
    h = q.shape[0]
    hkv = k.shape[0]
    q32 = q[:, :m].astype(jnp.float32)  # (h, m, d), pre-scaled
    qn = jnp.sqrt(jnp.sum(q32 * q32, axis=-1))  # (h, m)
    b = qn * knmax[:, None]
    if softcap2 is not None:
        b = jnp.minimum(b, softcap2)
    rows = jnp.arange(m, dtype=jnp.int32)
    valid = offsets[2]
    c_ref = None
    if causal and static_diag:
        kr = k[:, :n]  # row-aligned diagonal reference, pure elementwise
        excluded = jnp.zeros((m,), bool)
    elif causal:
        diag = rows + offsets[0] - offsets[1]  # this row's own kv column
        excluded = diag < 0  # whole local shard is in the row's future
        c_ref = jnp.clip(jnp.minimum(diag, valid - 1), 0, n - 1)
        if window is not None:
            in_win = c_ref >= diag - (window - 1)
            if sinks is not None:
                # out-of-band rows still attend sink column 0
                c_ref = jnp.where(in_win, c_ref, 0)
            else:
                # clipped diagonal below the band start <=> the band
                # misses the valid prefix entirely: nothing attended
                excluded = jnp.logical_or(excluded,
                                          jnp.logical_not(in_win))
        # gather in the STORAGE dtype; the cast fuses into the reduce
        # (an fp32 gather materializes 2x the bytes for nothing)
        kr = jnp.take(k[:, :n], c_ref, axis=1)  # (hkv, m, d)
    else:
        # column 0 for every row: (hkv, 1, d) broadcast, no gather
        kr = k[:, :1, :]
        excluded = jnp.zeros((m,), bool)
    excluded = jnp.logical_or(excluded, valid <= 0)
    s_ref = jnp.sum(
        q32.reshape(hkv, group, m, q32.shape[-1])
        * kr.astype(jnp.float32)[:, None], axis=-1
    ).reshape(h, m)
    if softcap2 is not None:
        # monotone, so cap(s_ref) <= cap(max_s): still a lower bound
        s_ref = softcap2 * jnp.tanh(s_ref / softcap2)
    over = b - s_ref
    if q_segment_ids is not None:
        kv_ids = jnp.asarray(kv_segment_ids, jnp.int32)
        if causal and static_diag:
            ref_ids = kv_ids  # row-aligned diagonal reference
        elif c_ref is None:
            ref_ids = kv_ids[0]
        else:
            ref_ids = jnp.take(kv_ids, c_ref)
        match = ref_ids == jnp.asarray(q_segment_ids, jnp.int32)
        over = jnp.where(match[None, :], over, jnp.inf)
    return jnp.max(jnp.where(excluded[None, :], 0.0, over))


def _flash_call(
    q: jax.Array,  # (H, m, d)
    k: jax.Array,  # (Hkv, n, d)
    v: jax.Array,  # (Hkv, n, dv)
    *,
    scale: float,
    causal: bool,
    normalize: bool,
    block_sizes: BlockSizes,
    return_stats: bool,
    interpret: bool,
    out_dtype,
    q_offset=None,
    kv_offset=None,
    kv_valid=None,
    q_segment_ids=None,
    kv_segment_ids=None,
    window=None,
    softcap=None,
    sinks=None,
    max_mode="online",
):
    h, m, d = q.shape
    hkv, n, dv = v.shape
    if max_mode not in MAX_MODES + ("auto",):
        raise ValueError(f"unknown max_mode {max_mode!r}")
    if h % hkv != 0:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    group = h // hkv
    segmented = q_segment_ids is not None
    if segmented != (kv_segment_ids is not None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    if window is not None:
        if not causal:
            raise ValueError(
                "window (sliding-window attention) requires causal=True"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if sinks is not None:
        if window is None:
            raise ValueError(
                "sinks (attention sinks) require window= (without a "
                "window every past position is attended anyway)"
            )
        if sinks < 1:
            raise ValueError(f"sinks must be >= 1, got {sinks}")
        if q_segment_ids is not None:
            # the sink mask pins ABSOLUTE buffer positions; in a packed
            # buffer only the first segment would get its sinks — reject
            # rather than silently diverge
            raise ValueError(
                "sinks do not compose with segment_ids (sink positions "
                "are absolute, not per-segment); unpack the batch"
            )
    check_softcap(softcap)

    # Fold softmax scale * log2(e) into Q once (an (m, d) multiply in
    # fp32) so the kernel never scales the (m, n) score matrix and all
    # exponentials are raw exp2 — see the log2-domain note in
    # `_flash_kernel`.  Casting back to q.dtype re-rounds bf16 inputs
    # (~2^-8 relative), which the old score-domain scaling avoided;
    # keeping the kernel input bf16 is what keeps QK^T on the fast MXU
    # path, and measured end-to-end error at seq=32k stays ~2e-4 — two
    # orders under the ±0.02 contract.
    q = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)

    block_q = min(block_sizes.block_q, _ceil_to(m, 128))
    block_k = min(block_sizes.block_k, _ceil_to(n, 128))
    m_pad = _ceil_to(m, block_q)
    n_pad = _ceil_to(n, block_k)
    if m_pad != m:
        q = jnp.pad(q, ((0, 0), (0, m_pad - m), (0, 0)))
    if n_pad != n:
        k = jnp.pad(k, ((0, 0), (0, n_pad - n), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, n_pad - n), (0, 0)))

    num_kv_blocks = n_pad // block_k
    sink_blocks = 0 if sinks is None else min(
        -(-sinks // block_k), num_kv_blocks
    )
    if window is None:
        band_blocks = num_kv_blocks
    else:
        # blocks covering [row - (window-1), row] for a block_q row span,
        # +1 for block misalignment; sink blocks prepend the band
        band_blocks = min(
            num_kv_blocks, -(-(window - 1 + block_q) // block_k) + 1
        )
    grid = (h, m_pad // block_q, sink_blocks + band_blocks)

    variant = max_mode
    if variant == "auto":
        # measured dispatch: the tuning tables (user cache, then the
        # shipped table) pick the rescaling math per (shape, dtype,
        # flags); a miss resolves to the online oracle — on CPU (no
        # tpu-* entries apply) "auto" is byte-identical to the default.
        variant = _tuned_max_mode(
            "flash_fwd", dtype=q.dtype, heads=h, seq=m, dim=d,
            causal=causal, window=window, stats=return_stats)
    bound_mode = variant == "bound"
    if bound_mode and window is not None:
        # Measured (round 5, device clock): on banded grids the bound
        # kernel's VPU saving is within noise of the online kernel
        # (w=1024@32k: 0.227 ms online vs 0.21 bound) while the
        # runtime overshoot guard is a FLAT cost that dwarfs the tiny
        # band kernel (+70% at w=1024).  Same outputs either way —
        # windowed calls statically resolve to the online recurrence.
        bound_mode = False
    if bound_mode and block_k % _STAT_LANES != 0:
        # the bound kernel accumulates l in _STAT_LANES-wide lane
        # slices (`_flash_tile`): a narrower tile cannot feed the
        # (block_q, _STAT_LANES) scratch (shape error), and a wider
        # NON-MULTIPLE tile silently drops columns past the last full
        # slice from l while still accumulating them into P·V —
        # measured 0.31 max abs error at block_k=192.  Both resolve to
        # the online recurrence (latent since round 3, exposed when
        # the sharded paths gained max_mode threading).
        bound_mode = False
    if bound_mode and (h * m_pad * n_pad * (0.5 if causal else 1.0)
                       < _BOUND_MIN_SCORE_ELEMS):
        # Measured crossover (round 5, device clock, d=128 single
        # head; scripts/guard_cost_exp.py, artifacts/guard_cost_exp
        # .json): the guard's flat ~9-30 us cond cost exceeds bound
        # mode's VPU saving on small grids — guarded bound loses to
        # online by 51% at 2k, 27% at 4k, 35% at causal 4k, and wins
        # from 8k (+6%) / causal 8k (+21%) up.  Same outputs either
        # way (bound is exact and demotes to online when unsafe), so
        # small calls statically resolve to the online recurrence;
        # the threshold sits between causal 4k (8.4M elems, online
        # side) and causal 8k (33.6M, bound side) with margin both
        # ways.  Grid work scales with h*m*n (halved causal), so the
        # dispatch uses score elements, mirroring the measurement.
        bound_mode = False
    if variant == "bound" and not bound_mode:
        variant = "online"
    if obs.is_enabled():
        # trace-time: one tick per lowering, recording the static
        # resolution (auto -> table pick, bound -> online demotions)
        _FLASH_LOWERED.inc(requested=max_mode, lowered=variant)
    softcap2 = None if softcap is None else softcap * _LOG2E
    kernel_kwargs = dict(
        n_true=n,
        block_k=block_k,
        causal=causal,
        block_q=block_q,
        normalize=normalize,
        out_dtype=out_dtype,
        dynamic_valid=kv_valid is not None,
        segmented=segmented,
        window=window,
        n_true_blocks=num_kv_blocks,
        softcap2=softcap2,
        sinks=sinks,
        sink_blocks=sink_blocks,
    )

    offsets = jnp.stack(
        [
            jnp.asarray(0 if q_offset is None else q_offset, dtype=jnp.int32),
            jnp.asarray(0 if kv_offset is None else kv_offset, dtype=jnp.int32),
            jnp.asarray(n if kv_valid is None else kv_valid, dtype=jnp.int32),
        ]
    )
    dynamic_valid = kv_valid is not None

    def kv_map(hh, i, j, off, knm):
        # Clamp block indices for tiles the kernel's @pl.when guard will
        # skip (above the causal diagonal / past the dynamic valid
        # prefix) to the last block it will compute: Pallas elides the
        # HBM->VMEM DMA when consecutive grid steps map to the same
        # block, so skipped tiles cost no bandwidth either.  The
        # clamped index always equals j for computed tiles (the clamp
        # bounds mirror the compute_tile conditions in `_flash_kernel`).
        if window is None:
            jj = j
        else:
            # banded grid: absolute block = band start + j, clipped to
            # the last real block (compute is guarded in-kernel);
            # mirrors the sink/band split in `_flash_kernel`
            base = jnp.maximum(
                (i * block_q + off[0] - off[1] - (window - 1)) // block_k,
                sink_blocks,
            )
            if sink_blocks:
                jj = jnp.where(
                    j < sink_blocks, j,
                    jnp.minimum(base + j - sink_blocks, num_kv_blocks - 1),
                )
            else:
                jj = jnp.minimum(base + j, num_kv_blocks - 1)
        if causal:
            causal_last = (
                i * block_q + block_q - 1 + off[0] - off[1]
            ) // block_k
            jj = jnp.minimum(jj, jnp.maximum(causal_last, 0))
        if dynamic_valid:
            valid_last = jnp.maximum((off[2] + block_k - 1) // block_k - 1, 0)
            jj = jnp.minimum(jj, valid_last)
        return (hh // group, jj, 0)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda hh, i, j, off, knm: (hh, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_map),
        pl.BlockSpec((1, block_k, dv), kv_map),
    ]
    if bound_mode:
        # Per-KV-head max key norm for the in-kernel Cauchy-Schwarz
        # bound on the log2-domain scores: |q·k| <= ||q||·max_j ||k_j||
        # (exact kernel operands: the pre-scaled, re-rounded Q — its
        # norm is computed in-kernel from the resident block — and the
        # padded K).  Softmax output and lse are invariant to the
        # choice of max as long as it is >= the true row max, so
        # overshoot costs only fp32 headroom — and that headroom is
        # ENFORCED at runtime: `_bound_overshoot_estimate` bounds the
        # worst-row overshoot from the same operands, and calls that
        # might leave the fp32 exp2 range (adversarial norms, LLM
        # outlier K channels) self-demote to the online kernel below.
        k32 = k.astype(jnp.float32)
        knmax = jnp.repeat(
            jnp.max(jnp.sqrt(jnp.sum(k32 * k32, axis=-1)), axis=-1),
            group,
        )  # (h,) f32, indexed by the head grid dim in `_init`
        bound_safe = (
            _bound_overshoot_estimate(
                q, k, knmax, offsets, m=m, n=n, group=group,
                causal=causal, window=window, sinks=sinks,
                softcap2=softcap2, q_segment_ids=q_segment_ids,
                kv_segment_ids=kv_segment_ids,
                # gather-free diagonal reference for plain causal
                # self-attention (the training/bench shape)
                static_diag=(causal and q_offset is None
                             and kv_offset is None and kv_valid is None
                             and m == n),
            )
            <= SAFE_OVERSHOOT_LOG2
        )
    else:
        knmax = jnp.zeros((1,), jnp.float32)  # unused placeholder
    seg_inputs = ()
    if segmented:
        q_rep, kv_rep = segment_masks(q_segment_ids, kv_segment_ids,
                                      m, n, m_pad, n_pad)
        seg_inputs = (q_rep, kv_rep)
        in_specs += [
            pl.BlockSpec((block_q, _STAT_LANES),
                         lambda hh, i, j, off, knm: (i, 0)),
            pl.BlockSpec(
                (8, block_k),
                lambda hh, i, j, off, knm: (0, kv_map(hh, i, j, off, knm)[1]),
            ),
        ]
    out_shapes = [jax.ShapeDtypeStruct((h, m_pad, dv), out_dtype)]
    out_specs = [
        pl.BlockSpec((1, block_q, dv), lambda hh, i, j, off, knm: (hh, i, 0))
    ]
    if return_stats:
        stat_shape = jax.ShapeDtypeStruct((h, m_pad, _STAT_LANES), jnp.float32)
        stat_spec = pl.BlockSpec(
            (1, block_q, _STAT_LANES), lambda hh, i, j, off, knm: (hh, i, 0)
        )
        out_shapes += [stat_shape, stat_shape]
        out_specs += [stat_spec, stat_spec]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, dv), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
        ],
    )

    # Raised scoped-VMEM budget for big tiles only (like the backward
    # kernels): the default ~16 MB budget rejects every tile bigger
    # than the round-3 defaults, cutting the sweep space off exactly at
    # the boundary those defaults sat on — the round-4 universal
    # 4096x2048 needs the raise.  Small tiles keep the default budget:
    # the raise measurably perturbed the windowed 512x512 kernel's
    # schedule (0.208 -> 0.251 ms at w=1024).
    big_tile = block_q * block_k > 2 * 2**20
    compiler_params = _compiler_params(
        ("parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=110 * 2**20 if big_tile else None)

    # windowed grids only visit the band's KV columns
    n_eff = band_blocks * block_k
    flops = 2 * h * m_pad * n_eff * (d + dv)

    def _run(variant_: str):
        kern = functools.partial(_flash_kernel, variant=variant_,
                                 **kernel_kwargs)
        if not return_stats:
            kern = functools.partial(_no_stat_kernel, kern)
        return pl.pallas_call(
            kern,
            grid_spec=grid_spec,
            out_shape=out_shapes,
            compiler_params=compiler_params,
            cost_estimate=pl.CostEstimate(
                flops=flops,
                bytes_accessed=int(
                    (q.size + (k.size + v.size) * n_eff // n_pad)
                    * q.dtype.itemsize
                )
                + h * m_pad * dv * 4,
                transcendentals=h * m_pad * n_eff,
            ),
            interpret=interpret,
        )(offsets, knmax, q, k, v, *seg_inputs)

    if bound_mode:
        # Self-demotion (runtime, data-dependent): the bound kernel is
        # provably exact only while the overshoot stays inside fp32
        # exp2 range; past SAFE_OVERSHOOT_LOG2 the online kernel runs
        # instead.  Both branches compile once; the predicate is a
        # scalar and the guard's own cost is O(m*d) — ~1% of a 32k
        # forward, 0 of the grid's FLOPs.
        if _UNSAFE_SKIP_GUARD:
            # Perf-triage hatch (module global, code-settable only — a
            # process env var would silently disable the guard
            # fleet-wide and be frozen into jit caches): runs the bound
            # kernel with no guard/cond — WRONG (all-zero rows) on
            # inputs whose overshoot leaves fp32 exp2 range.
            _logger.warning(
                "_UNSAFE_SKIP_GUARD is set — bound-mode overshoot "
                "guard DISABLED (triage only)")
            outs = _run("bound")
        else:
            # The cond's STRUCTURE costs ~30-50 us per call on this
            # toolchain regardless of branch content — measured round 5
            # (scripts/guard_cost_exp.py, scripts/passthrough_cond_exp
            # .py, artifacts/guard_cost_exp.json): a trivial-predicate
            # cond pays the same, a pass-through-branch cond pays MORE
            # (37-52 us), and moving the branch in-kernel (one kernel,
            # two grid-invariant @pl.when tile bodies reading the
            # verdict from a scalar-prefetch slot) ran 359 us vs 214 at
            # 8k — Mosaic schedules the union CFG without cross-step
            # overlap, the causal-split lesson again.  Since guarded
            # bound (214 us @8k) still beats online (228 us), this cond
            # IS the measured optimum among every structure tried; the
            # flat cost is the price of the no-silent-zeros guarantee.
            outs = jax.lax.cond(bound_safe,
                                lambda: _run("bound"),
                                lambda: _run("online"))
    else:
        outs = _run(variant)

    out = outs[0][:, :m]
    if return_stats:
        row_max = outs[1][:, :m, 0]
        row_sum = outs[2][:, :m, 0]
        return out, row_max, row_sum
    return out


def _no_stat_kernel(kernel, *args):
    # args = (off, knm, q, k, v, [q_seg, kv_seg], o, acc, m, l): splice
    # None stat-output refs in front of the scratch refs.
    *pre, o_ref, acc, m_scr, l_scr = args
    kernel(*pre, o_ref, None, None, acc, m_scr, l_scr)


def segment_masks(q_seg, kv_seg, m: int, n: int, m_pad: int, n_pad: int):
    """Mosaic-legal segment-id layouts for the flash kernels.

    A narrow (1, block) id vector violates the (8, 128) min-tile rule,
    so ids ship replicated: Q ids lane-replicated (m_pad, _STAT_LANES),
    KV ids sublane-replicated (8, n_pad).  Ids must match the TRUE
    sequence lengths (m, n); only kernel padding gets id -1 (matches
    nothing; real ids are assumed non-negative).
    """
    q_seg = jnp.asarray(q_seg, jnp.int32)
    kv_seg = jnp.asarray(kv_seg, jnp.int32)
    if q_seg.shape != (m,) or kv_seg.shape != (n,):
        raise ValueError(
            f"segment id shapes {q_seg.shape}/{kv_seg.shape} != "
            f"({m},)/({n},)"
        )
    if m_pad != m:
        q_seg = jnp.pad(q_seg, (0, m_pad - m), constant_values=-1)
    if n_pad != n:
        kv_seg = jnp.pad(kv_seg, (0, n_pad - n), constant_values=-1)
    q_rep = jnp.broadcast_to(q_seg[:, None], (m_pad, _STAT_LANES))
    kv_rep = jnp.broadcast_to(kv_seg[None, :], (8, n_pad))
    return q_rep, kv_rep


def check_softcap(softcap) -> None:
    """Shared entry-point validation for the softcap knob."""
    if softcap is not None and softcap <= 0.0:
        raise ValueError(f"softcap must be > 0, got {softcap}")


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _canon(q, k, v):
    """Canonicalize (m, d) / (h, m, d) inputs to (h, m, d); return unbatcher."""
    if q.ndim != k.ndim or q.ndim != v.ndim:
        raise ValueError(f"rank mismatch: Q{q.shape} K{k.shape} V{v.shape}")
    if q.shape[-1] != k.shape[-1] or k.shape[-2] != v.shape[-2]:
        raise ValueError(f"shape mismatch: Q{q.shape} K{k.shape} V{v.shape}")
    if k.shape[:-2] != v.shape[:-2]:
        raise ValueError(f"K/V head dims differ: K{k.shape} V{v.shape}")
    if q.ndim == 4 and q.shape[0] != k.shape[0]:
        raise ValueError(f"batch mismatch: Q{q.shape} K{k.shape}")
    if q.ndim >= 3 and q.shape[-3] % k.shape[-3] != 0:
        raise ValueError(
            f"q heads {q.shape[-3]} not a multiple of kv heads {k.shape[-3]}"
        )
    if q.ndim == 2:
        return q[None], k[None], v[None], lambda o: o[0]
    if q.ndim == 3:
        return q, k, v, lambda o: o
    if q.ndim == 4:  # (B, H, m, d): fold batch into heads
        b, h, m_len, d = q.shape
        bk, hkv, n_len, dkk = k.shape
        qf = q.reshape(b * h, m_len, d)
        kf = k.reshape(bk * hkv, n_len, dkk)
        vf = v.reshape(bk * hkv, n_len, v.shape[-1])
        # Folding batch outside heads keeps q-head→kv-head grouping contiguous
        # only within a batch element; regroup so index h//group is right:
        # q heads of batch b occupy [b*h, (b+1)*h) and kv heads [b*hkv, ...).
        return qf, kf, vf, lambda o: o.reshape(b, h, m_len, -1)
    raise ValueError(f"unsupported rank {q.ndim} for flash attention")


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale",
        "causal",
        "block_sizes",
        "interpret",
        "window",
        "softcap",
        "sinks",
        "max_mode",
    ),
)
def _flash_attention_jit(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    causal: bool = False,
    block_sizes: BlockSizes | None = None,
    interpret: bool | None = None,
    q_offset=None,
    kv_offset=None,
    kv_valid=None,
    q_segment_ids=None,
    kv_segment_ids=None,
    window: int | None = None,
    softcap: float | None = None,
    sinks: int | None = None,
    max_mode: str = "online",
) -> jax.Array:
    """Fused single-device attention: softmax(q k^T * scale) v.

    Accepts (m, d), (h, m, d) or (b, h, m, d) inputs; for 3D/4D inputs the
    number of KV heads may divide the number of Q heads (GQA — BASELINE
    config 5: 32 Q heads sharing 4 KV heads).  ``q_offset``/``kv_offset``
    (dynamic scalars) give the global sequence positions of the local Q/KV
    rows for causal masking over shards.  ``q_segment_ids``/
    ``kv_segment_ids`` ((m,)/(n,) non-negative int32, shared across
    heads) mask attention across packed-sequence boundaries.  ``window``
    (static int, requires causal) keeps the last ``window`` positions per
    query — sliding-window attention; skipped tiles cost no FLOPs.
    ``softcap`` (static float) applies Gemma-2-style logit capping
    ``cap * tanh(scores / cap)`` before masking and softmax.  ``sinks``
    (static int, requires window) keeps the first ``sinks`` positions
    attendable alongside the window (StreamingLLM attention sinks).
    ``max_mode="bound"`` (VFA, PAPERS.md) replaces the in-kernel online
    max with a precomputed Cauchy-Schwarz row bound — same output and
    stats (softmax is max-choice invariant), shorter per-tile VPU chain.
    Bound mode is runtime-guarded: when the estimated worst-row
    overshoot could leave fp32 exp2 range (adversarial norms, outlier K
    channels), the call self-demotes to the online kernel
    (`_bound_overshoot_estimate`), so the result is exact either way.
    ``max_mode="flashd"`` (FLASH-D) folds the softmax division into the
    accumulator update (no rescale multiply, no division epilogue);
    ``max_mode="amla"`` (AMLA) quantizes the running max to powers of
    two so rescales become exponent-field integer adds — both same
    semantics, fuzzed against the fp64 oracle (`chaos`).
    ``max_mode="auto"`` asks the tuning tables (measured per shape,
    dtype, flags) and falls back to ``"online"`` on a miss.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    if q_segment_ids is not None and q.ndim == 4:
        raise ValueError(
            "segment ids support 2D/3D inputs (ids shared across heads); "
            "vmap over the batch for per-sequence ids"
        )
    qh, kh, vh, unbatch = _canon(q, k, v)
    out = _flash_call(
        qh,
        kh,
        vh,
        scale=scale,
        causal=causal,
        normalize=True,
        block_sizes=block_sizes or BlockSizes.for_shape(
            qh.shape[0], qh.shape[1], qh.shape[2], window,
            causal=causal, dtype=qh.dtype),
        return_stats=False,
        interpret=interpret,
        out_dtype=v.dtype,
        q_offset=q_offset,
        kv_offset=kv_offset,
        kv_valid=kv_valid,
        q_segment_ids=q_segment_ids,
        kv_segment_ids=kv_segment_ids,
        window=window,
        softcap=softcap,
        sinks=sinks,
        max_mode=max_mode,
    )
    return unbatch(out)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    **kwargs) -> jax.Array:
    """Fused single-device attention: softmax(q k^T * scale) v.

    Thin dispatch shim over the jitted kernel (same signature — see
    :func:`_flash_attention_jit` for the full parameter docs) that
    ticks the op-dispatch telemetry when `attention_tpu.obs` is
    enabled; disabled (the default) it is one flag check."""
    if obs.is_enabled():
        _FLASH_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[-2], q.shape[-1]),
            mode=str(kwargs.get("max_mode", "online")),
            entry="attention")
    return _flash_attention_jit(q, k, v, **kwargs)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "causal", "block_sizes", "interpret",
                     "window", "softcap", "sinks", "max_mode"),
)
def _flash_attention_partials_jit(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    causal: bool = False,
    block_sizes: BlockSizes | None = None,
    interpret: bool | None = None,
    q_offset=None,
    kv_offset=None,
    kv_valid=None,
    q_segment_ids=None,
    kv_segment_ids=None,
    window: int | None = None,
    softcap: float | None = None,
    sinks: int | None = None,
    max_mode: str = "online",
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Unnormalized attention over a local KV shard.

    Returns ``(out_unnorm, row_max, row_sumexp)`` in float32 — the
    per-shard (contrib, lmax, lsum) triple of the reference's local online
    softmax pass (`attention-mpi.c:168-189`), ready for the global
    two-phase pmax/psum merge.  Shapes: out (..., m, dv), stats (..., m).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    if q_segment_ids is not None and q.ndim == 4:
        raise ValueError(
            "segment ids support 2D/3D inputs (ids shared across heads)"
        )
    qh, kh, vh, unbatch = _canon(q, k, v)
    out, row_max, row_sum = _flash_call(
        qh,
        kh,
        vh,
        scale=scale,
        causal=causal,
        normalize=False,
        block_sizes=block_sizes or BlockSizes.for_shape(
            qh.shape[0], qh.shape[1], qh.shape[2], window,
            returns_stats=True, causal=causal, dtype=qh.dtype),
        return_stats=True,
        interpret=interpret,
        out_dtype=jnp.float32,
        q_offset=q_offset,
        kv_offset=kv_offset,
        kv_valid=kv_valid,
        q_segment_ids=q_segment_ids,
        kv_segment_ids=kv_segment_ids,
        window=window,
        softcap=softcap,
        sinks=sinks,
        max_mode=max_mode,
    )
    if q.ndim == 2:
        return out[0], row_max[0], row_sum[0]
    if q.ndim == 4:
        b, h = q.shape[:2]
        return (
            out.reshape(b, h, *out.shape[1:]),
            row_max.reshape(b, h, -1),
            row_sum.reshape(b, h, -1),
        )
    return out, row_max, row_sum


def flash_attention_partials(
    q: jax.Array, k: jax.Array, v: jax.Array, **kwargs
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Unnormalized attention over a local KV shard (telemetry shim;
    see :func:`_flash_attention_partials_jit` for the full docs)."""
    if obs.is_enabled():
        _FLASH_CALLS.inc(
            bucket=obs.shape_bucket(q.shape[-2], q.shape[-1]),
            mode=str(kwargs.get("max_mode", "online")),
            entry="partials")
    return _flash_attention_partials_jit(q, k, v, **kwargs)
