"""Pallas flash-attention backward kernels for TPU.

The reference is forward-only (no backward exists in `attention.c` /
`attention-mpi.c`); this is new training surface.  The math is the
standard flash backward — recompute P tile-wise from the saved
log-sum-exp, then

    P  = exp(S - lse)            D  = rowsum(dO ∘ O)   (precomputed)
    dV = Pᵀ dO                   dS = P ∘ (dO Vᵀ - D)
    dQ = scale · dS K            dK = scale · dSᵀ Q

— executed as two Pallas kernels instead of blocked XLA einsums:

  * **dQ kernel**: grid (head, q-block, kv-block), kv innermost; dQ
    accumulates in VMEM scratch across the KV sweep (the mirror of the
    forward's online accumulator).
  * **dK/dV kernel**: grid (kv-block, q-head, q-block) with the q-head
    dimension ordered so all Q heads sharing one KV head (GQA) form a
    contiguous run — dK/dV accumulate across the whole run in VMEM
    scratch and are written once per KV head.  The grouped reduction
    never materializes `jnp.repeat`-expanded gradients in HBM.

Tiles are **Q-major** ((block_q, block_k)), matching the forward
kernel: the per-row stats lse/D enter lane-replicated as
(block_q, _STAT_LANES) blocks — the same layout the forward emits —
because Mosaic requires the last two block dims to be (8k, 128m), which
a narrow (1, block_q) row-vector block violates.  Lane-replicated
stats reduce to (block_q, 1) columns with no in-kernel transposes, and
the MXU contracts over either operand dimension, so Pᵀ dO / dSᵀ Q are
single dot_generals on the Q-major tiles.

Domain bookkeeping matches the forward (`flash.py::_flash_call`): Q is
pre-scaled by scale·log2(e) and re-rounded to the input dtype, so scores
are log2-domain and P = exp2(S₂ - lse₂) reproduces the forward's exact
probabilities; dK picks up a ln2 factor (dK = ln2 · dSᵀ Q_scaled) and dQ
the plain `scale` (contraction against unscaled K).

Sliding-window note: like the forward kernel, windowed backward uses
banded grids — the dQ kernel's KV sweep and the dK/dV kernel's Q sweep
cover only the blocks the window can touch (skipped grid steps are not
free: they pay un-overlapped DMA latency), so windowed backward
wall-time scales with the window, not the sequence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu.ops.flash import (
    _LN2,
    _LOG2E,
    _STAT_LANES,
    NEG_INF,
    BlockSizes,
    _big_tile_device,
    _ceil_to,
    _compiler_params,
    _vmem_limit_supported,
)


def _stat_col(ref):
    """Lane-replicated (block_q, _STAT_LANES) stat block -> (block_q, 1)."""
    return jnp.max(ref[0], axis=-1, keepdims=True)


def _recompute_p(qs, k, lse_col, *, causal, q_base, k_base,
                 q_off=0, kv_off=0, valid=None,
                 q_seg_ref=None, kv_seg_ref=None, window=None,
                 softcap2=None):
    """(block_q, block_k) probability tile, Q-major; returns (p, dcap)
    where ``dcap`` is the softcap derivative factor 1 - tanh^2 (None
    when no softcap).

    ``qs`` is the forward's pre-scaled Q (scores come out log2-domain),
    ``lse_col`` a (block_q, 1) log2-domain log-sum-exp column.
    ``q_off``/``kv_off`` are the global positions of this call's local
    Q/KV row 0 (dynamic scalars — causal masking stays correct when the
    caller holds only a shard, the forward kernel's offsets contract);
    ``valid`` is a traced count of valid LOCAL KV rows, or None when
    every row is real.
    """
    s2 = jax.lax.dot_general(
        qs, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (block_q, block_k)
    dcap = None
    if softcap2 is not None:
        t = jnp.tanh(s2 / softcap2)
        s2 = softcap2 * t
        dcap = 1.0 - t * t
    p = jnp.exp2(s2 - lse_col)
    mask = None
    if causal or valid is not None:
        row = q_base + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
        col = k_base + jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    if valid is not None:
        # rows the forward fully masked have lse == -inf (guard them too:
        # exp2(s - -inf) would be +inf, not 0)
        mask = jnp.logical_and(col < valid, lse_col != NEG_INF)
    if causal:
        # also guards rows the forward fully masked (lse == -inf)
        cm = jnp.logical_and(col + kv_off <= row + q_off,
                             lse_col != NEG_INF)
        mask = cm if mask is None else jnp.logical_and(mask, cm)
        if window is not None:
            mask = jnp.logical_and(
                mask, col + kv_off >= row + q_off - (window - 1))
    if q_seg_ref is not None:
        q_ids = jnp.max(q_seg_ref[...], axis=-1, keepdims=True)
        kv_ids = jnp.max(kv_seg_ref[...], axis=0, keepdims=True)
        seg = q_ids == kv_ids
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    return p, dcap


def _p_and_ds(qs, k, v, do, lse_ref, delta_ref, *, causal, q_base, k_base,
              q_off, kv_off, valid, q_seg_ref, kv_seg_ref, window,
              softcap2):
    """Shared tile derivation for all three backward kernels: recompute
    P from the saved lse, form dP = dO Vᵀ and dS = P ∘ (dP - D) with the
    softcap chain factor applied.  One definition keeps the fused and
    two-kernel gradients provably identical."""
    p, dcap = _recompute_p(
        qs, k, _stat_col(lse_ref), causal=causal,
        q_base=q_base, k_base=k_base, q_off=q_off, kv_off=kv_off,
        valid=valid, q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref,
        window=window, softcap2=softcap2,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (block_q, block_k) = dO Vᵀ
    ds = p * (dp - _stat_col(delta_ref))
    if dcap is not None:
        ds = ds * dcap  # chain through cap*tanh(s/cap)
    return p, ds


def _dq_kernel(
    offsets_ref, lse_ref, delta_ref, qs_ref, k_ref, v_ref, do_ref, *rest,
    causal, block_q, block_k, scale, out_dtype, compute_dtype, segmented,
    window, n_j_total, softcap2, dynamic_valid,
):
    if segmented:
        q_seg_ref, kv_seg_ref, *rest = rest
    else:
        q_seg_ref = kv_seg_ref = None
    dq_ref, acc_scr = rest
    q_off = offsets_ref[0]
    kv_off = offsets_ref[1]
    jb = pl.program_id(2)
    q_base = pl.program_id(1) * block_q
    if window is None:
        j = jb
    else:
        # banded grid (mirrors the forward kernel): skipped grid steps
        # are not free, so the j dimension covers only the window band
        j = jnp.maximum(
            (q_base + q_off - kv_off - (window - 1)) // block_k, 0
        ) + jb
    k_base = j * block_k

    @pl.when(jb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        qs, k = qs_ref[0], k_ref[0]
        _, ds = _p_and_ds(
            qs, k, v_ref[0], do_ref[0], lse_ref, delta_ref,
            causal=causal, q_base=q_base, k_base=k_base,
            q_off=q_off, kv_off=kv_off,
            valid=offsets_ref[2] if dynamic_valid else None,
            q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref, window=window,
            softcap2=softcap2,
        )
        acc_scr[...] += jax.lax.dot_general(
            ds.astype(compute_dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, d) = dS K

    keep = True
    guarded = False
    if causal:
        # KV tiles strictly above the diagonal are all zeros under the
        # causal mask — skip them (halves causal backward FLOPs); the
        # banded window grid can also run past the last real KV block.
        keep = jnp.logical_and(
            keep, k_base + kv_off <= q_base + block_q - 1 + q_off
        )
        guarded = True
        if window is not None:
            keep = jnp.logical_and(keep, j < n_j_total)
    if dynamic_valid:
        # blocks wholly past the valid KV prefix contribute nothing
        keep = jnp.logical_and(keep, k_base < offsets_ref[2])
        guarded = True
    if guarded:
        pl.when(keep)(_compute)
    else:
        _compute()

    @pl.when(jb == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = (acc_scr[...] * scale).astype(out_dtype)


def _dkv_kernel(
    offsets_ref, lse_ref, delta_ref, qs_ref, k_ref, v_ref, do_ref, *rest,
    causal, block_q, block_k, group, compute_dtype, segmented, window,
    n_i_total, softcap2, dynamic_valid,
):
    if segmented:
        q_seg_ref, kv_seg_ref, *rest = rest
    else:
        q_seg_ref = kv_seg_ref = None
    dk_ref, dv_ref, dk_scr, dv_scr = rest
    q_off = offsets_ref[0]
    kv_off = offsets_ref[1]
    h = pl.program_id(1)
    ib = pl.program_id(2)
    h_in_group = jax.lax.rem(h, group)
    k_base = pl.program_id(0) * block_k
    if window is None:
        i = ib
    else:
        # banded: only q blocks within [diagonal, diagonal + window)
        # contribute to this kv block (diagonal in LOCAL q coordinates:
        # the first local q row that can see local kv row k_base)
        i = jnp.maximum(
            (k_base + kv_off - q_off) // block_q, 0
        ) + ib
    q_base = i * block_q

    @pl.when(jnp.logical_and(h_in_group == 0, ib == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        qs, do = qs_ref[0], do_ref[0]
        p, ds = _p_and_ds(
            qs, k_ref[0], v_ref[0], do, lse_ref, delta_ref,
            causal=causal, q_base=q_base, k_base=k_base,
            q_off=q_off, kv_off=kv_off,
            valid=offsets_ref[2] if dynamic_valid else None,
            q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref, window=window,
            softcap2=softcap2,
        )
        dv_scr[...] += jax.lax.dot_general(
            p.astype(compute_dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, dv) = Pᵀ dO — contraction over the q dim
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(compute_dtype), qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, d) = dSᵀ Q_scaled

    keep = True
    guarded = False
    if causal:
        # Q tiles wholly above the diagonal contribute nothing to this
        # KV block — skip them (halves causal backward FLOPs); the
        # banded window grid can also run past the last real Q block.
        keep = jnp.logical_and(
            keep, k_base + kv_off <= q_base + block_q - 1 + q_off
        )
        guarded = True
        if window is not None:
            # band_i overestimates by one tile when block_k % block_q
            # == 0: also skip q tiles wholly past the window end
            keep = jnp.logical_and(keep, i < n_i_total)
            keep = jnp.logical_and(
                keep,
                q_base + q_off - (window - 1)
                <= k_base + block_k - 1 + kv_off,
            )
    if dynamic_valid:
        keep = jnp.logical_and(keep, k_base < offsets_ref[2])
        guarded = True
    if guarded:
        pl.when(keep)(_compute)
    else:
        _compute()

    @pl.when(
        jnp.logical_and(
            h_in_group == group - 1, ib == pl.num_programs(2) - 1
        )
    )
    def _finalize():
        # Q_scaled carries scale·log2(e); ln2 restores the plain `scale`.
        dk_ref[0] = dk_scr[...] * _LN2
        dv_ref[0] = dv_scr[...]


def _fused_bwd_kernel(
    offsets_ref, lse_ref, delta_ref, qs_ref, k_ref, v_ref, do_ref,
    *rest,
    causal, block_q, block_k, scale, compute_dtype, softcap2,
    dynamic_valid, window, n_i_total, segmented,
):
    """Single-pass fused backward: S, dO·Vᵀ and dS are computed ONCE per
    (q, kv) tile and all three gradients come out of the same sweep —
    10·m·n·d backward matmul FLOPs, the algorithmic minimum under lse
    recompute, vs the two-kernel path's 14·m·n·d (which re-derives S and
    dO·Vᵀ in both kernels).

    Grid is (head, kv-block, q-block) with the q sweep innermost:

      * dK/dV accumulate in VMEM scratch across the q sweep and are
        written once per (head, kv-block) — per-Q-head PARTIALS under
        GQA (the group sum is a cheap XLA reduction outside; unlike the
        two-kernel dK/dV kernel there is no in-kernel group run).
      * dQ accumulates directly in its OUTPUT block: the out spec maps
        on the head alone, so the whole (m_pad, d) fp32 buffer stays
        VMEM-resident across the entire (kv, q) sweep of one head and is
        DMA'd out exactly once — the revisits are all consecutive, which
        is what makes out-ref accumulation legal.  This is also the
        kernel's capacity bound: m_pad·d fp32 (double-buffered) must fit
        VMEM next to the tiles, so `flash_backward` only dispatches here
        for m_pad ≤ ~32k at d=128 (the benchmark headline shape).
    """
    if segmented:
        q_seg_ref, kv_seg_ref, *rest = rest
    else:
        q_seg_ref = kv_seg_ref = None
    dq_ref, dkp_ref, dvp_ref, dk_scr, dv_scr = rest
    q_off = offsets_ref[0]
    kv_off = offsets_ref[1]
    jb = pl.program_id(1)
    ib = pl.program_id(2)
    k_base = jb * block_k
    if window is None:
        i = ib
    else:
        # banded q sweep (mirrors the two-kernel dK/dV kernel): only q
        # blocks within [diagonal, diagonal + window) touch kv block jb
        i = jnp.maximum((k_base + kv_off - q_off) // block_q, 0) + ib
    q_base = i * block_q

    @pl.when(jnp.logical_and(jb == 0, ib == 0))
    def _zero_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(ib == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        qs, k, do = qs_ref[0], k_ref[0], do_ref[0]
        p, ds = _p_and_ds(
            qs, k, v_ref[0], do, lse_ref, delta_ref,
            causal=causal, q_base=q_base, k_base=k_base,
            q_off=q_off, kv_off=kv_off,
            valid=offsets_ref[2] if dynamic_valid else None,
            q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref, window=window,
            softcap2=softcap2,
        )
        dv_scr[...] += jax.lax.dot_general(
            p.astype(compute_dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, dv) = Pᵀ dO
        ds_c = ds.astype(compute_dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds_c, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, d) = dSᵀ Q_scaled
        dq_tile = jax.lax.dot_general(
            ds_c, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, d) = dS K
        sl = pl.dslice(q_base, block_q)
        dq_ref[0, sl, :] += dq_tile * scale

    keep = True
    guarded = False
    if causal:
        # q tiles wholly above the diagonal contribute nothing
        keep = jnp.logical_and(
            keep, k_base + kv_off <= q_base + block_q - 1 + q_off
        )
        guarded = True
        if window is not None:
            # the banded sweep can overrun the real q blocks, and can
            # include q tiles wholly past the window end
            keep = jnp.logical_and(keep, i < n_i_total)
            keep = jnp.logical_and(
                keep,
                q_base + q_off - (window - 1)
                <= k_base + block_k - 1 + kv_off,
            )
    if dynamic_valid:
        keep = jnp.logical_and(keep, k_base < offsets_ref[2])
        guarded = True
    if guarded:
        pl.when(keep)(_compute)
    else:
        _compute()

    @pl.when(ib == pl.num_programs(2) - 1)
    def _finalize():
        # Q_scaled carries scale·log2(e); ln2 restores the plain `scale`.
        dkp_ref[0] = dk_scr[...] * _LN2
        dvp_ref[0] = dv_scr[...]


# VMEM budget for the fused kernel's working set (dQ out block,
# double-buffered, plus the fp32 P/dP/dS tile temporaries and the
# double-buffered input blocks).  88 MB reproduces the on-chip
# compile-success boundary: 512x4096 and 1024x2048 at 32k compile
# (~70 MB by this model), 1024x4096 / 2048x2048 / 512x8192 do not
# (~100 MB).
_FUSED_VMEM_BUDGET = 88 * 2**20

# Q-row chunk sizes tried (largest first) when a sequence exceeds the
# fused kernel's resident-dQ budget as a whole — see the chunk loop in
# `flash_backward`.  Module-level so tests can shrink it to exercise
# the chunked path at test scale.
_FUSED_CHUNK_CANDIDATES = (65536, 32768, 16384, 8192)

# Perf-triage/tuning ONLY (the `_UNSAFE_SKIP_GUARD` precedent in
# flash.py: a code-settable module global, not an env var): force the
# two-kernel backward even where the fused plan fits.  The tuner's
# "flash_bwd" family sets this around its sweep — its entries feed
# `default_bwd_block_sizes`, which only governs the non-fused dispatch,
# so measuring them through the fused kernel would tune the wrong path.
_FORCE_TWO_KERNEL = False


def _fused_plan(m, n, d, dv, block_sizes, dtype, window=None):
    """The (BlockSizes, vmem_estimate) the fused kernel would run with,
    or None when its working set (including the caller's explicit tiles
    and the REAL block-multiple padding) exceeds the VMEM budget."""
    bs = block_sizes or default_fused_bwd_block_sizes(d, dtype, window,
                                                      m=m, n=n)
    bq = min(bs.block_q, _ceil_to(m, 128))
    bk = min(bs.block_k, _ceil_to(n, 128))
    m_pad = _ceil_to(m, bq)
    itemsize = jnp.dtype(dtype).itemsize
    vmem = (
        2 * m_pad * d * 4           # double-buffered dQ out block
        + 4 * bq * bk * 4           # P/dP/dS fp32 tile temporaries
        + 2 * (bq + bk) * (d + dv) * itemsize  # in blocks, double-buffered
        + bk * (d + dv) * 4         # dK/dV scratch accumulators
    )
    if vmem > _FUSED_VMEM_BUDGET:
        return None
    return bs


def _fused_chunk_choice(m, n, d, dv, block_sizes, dtype, *, window,
                        segmented):
    """The Q-row chunk size the chunked-fused path would use, or None
    when that path can't serve the call (feature flags, explicit tiles,
    whole-m already fits, or no candidate fits VMEM).  The SINGLE
    eligibility definition shared by `flash_backward`'s dispatch and
    `fused_backward_applicable` — bench.py keys FLOP accounting off the
    latter, so the two must never drift.  Sinks deliberately do NOT
    gate chunking: each chunk patches its sink sliver via per-chunk
    q_offset (`_sink_patch`), so they are chunk-compatible by design."""
    if (segmented or block_sizes is not None
            or not _vmem_limit_supported() or not _big_tile_device()
            or _fused_plan(m, n, d, dv, None, dtype, window) is not None):
        return None
    return next(
        (c for c in _FUSED_CHUNK_CANDIDATES
         if c < m and _fused_plan(c, n, d, dv, None, dtype, window)),
        None,
    )


def fused_backward_applicable(m: int, d: int, *, window, sinks,
                              segmented: bool, n: int | None = None,
                              dv: int | None = None,
                              block_sizes: BlockSizes | None = None,
                              dtype=jnp.bfloat16) -> bool:
    """True when `flash_backward` will take the fused single-pass kernel
    — whole (the resident-dQ plan fits) or Q-chunked (default tiles
    only, any chunk candidate fits).  bench.py keys its executed-FLOPs
    accounting off this: fused executes 10·mnd backward FLOPs, the
    two-kernel path 14·mnd.  ``sinks`` stays in the signature so
    callers describe the full call, but never gates eligibility —
    sinks are chunk-compatible by design (`_fused_chunk_choice`)."""
    if not _vmem_limit_supported() or not _big_tile_device():
        return False
    n_eff = n if n is not None else m
    dv_eff = dv if dv is not None else d
    if _fused_plan(m, n_eff, d, dv_eff, block_sizes, dtype,
                   window) is not None:
        return True  # segments ride whole-fused; chunking excludes them
    return _fused_chunk_choice(
        m, n_eff, d, dv_eff, block_sizes, dtype,
        window=window, segmented=segmented) is not None


def _fused_backward(qs, k, v, lse_rep, delta_rep, do, offsets, *,
                    h, hkv, m_pad, n_pad, d, dv, causal, scale,
                    block_q, block_k, softcap, dynamic_valid, interpret,
                    window=None, seg_inputs=()):
    """Drive `_fused_bwd_kernel`; returns (dq, dk, dv) with dk/dv already
    group-summed (fp32)."""
    group = h // hkv
    num_i = m_pad // block_q
    num_j = n_pad // block_k
    if window is None:
        band_i = num_i
    else:
        # banded: q blocks within [diagonal, diagonal + window) per kv
        # block (same bound as the two-kernel dK/dV kernel)
        band_i = min(num_i, (block_k - 1 + window - 1) // block_q + 2)

    def i_c(jj, ii, off):
        # Map the grid's ii to the absolute q block and clamp skipped
        # steps to a block the sweep does compute: Pallas elides the
        # HBM->VMEM DMA when consecutive grid steps map to the same
        # block, so causally skipped (and band-overrun) steps stop
        # fetching q/dO/stat blocks they never read.  The clamp equals
        # the true index for every computed step (same bounds as the
        # kernel's keep guard).
        i0 = jnp.maximum(
            (jj * block_k + off[1] - off[0]) // block_q, 0
        )
        if window is None:
            ii_abs = jnp.maximum(ii, i0) if causal else ii
        else:
            win_last = jnp.maximum(
                (jj * block_k + block_k - 1 + window - 1
                 + off[1] - off[0]) // block_q,
                0,
            )
            ii_abs = jnp.minimum(i0 + ii, win_last)
        return jnp.minimum(ii_abs, num_i - 1)

    stat_spec = pl.BlockSpec(
        (1, block_q, _STAT_LANES),
        lambda hh, jj, ii, off: (hh, i_c(jj, ii, off), 0),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, num_j, band_i),
        in_specs=[
            stat_spec,
            stat_spec,
            pl.BlockSpec((1, block_q, d),
                         lambda hh, jj, ii, off: (hh, i_c(jj, ii, off), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda hh, jj, ii, off: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_k, dv),
                         lambda hh, jj, ii, off: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_q, dv),
                         lambda hh, jj, ii, off: (hh, i_c(jj, ii, off), 0)),
            *([
                pl.BlockSpec((block_q, _STAT_LANES),
                             lambda hh, jj, ii, off: (i_c(jj, ii, off), 0)),
                pl.BlockSpec((8, block_k),
                             lambda hh, jj, ii, off: (0, jj)),
            ] if seg_inputs else []),
        ],
        out_specs=[
            pl.BlockSpec((1, m_pad, d), lambda hh, jj, ii, off: (hh, 0, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda hh, jj, ii, off: (hh, jj, 0)),
            pl.BlockSpec((1, block_k, dv),
                         lambda hh, jj, ii, off: (hh, jj, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, dv), jnp.float32),
        ],
    )
    dq, dkp, dvp = pl.pallas_call(
        functools.partial(
            _fused_bwd_kernel,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            scale=scale,
            compute_dtype=qs.dtype,
            softcap2=None if softcap is None else softcap * _LOG2E,
            dynamic_valid=dynamic_valid,
            window=window,
            n_i_total=num_i,
            segmented=bool(seg_inputs),
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((h, m_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((h, n_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((h, n_pad, dv), jnp.float32),
        ],
        compiler_params=_compiler_params(
            ("parallel", "arbitrary", "arbitrary"),
            vmem_limit_bytes=110 * 2**20),
        cost_estimate=pl.CostEstimate(
            # executed tiles = num_j x band_i (banded under window)
            flops=10 * h * n_pad * (band_i * block_q) * d,
            bytes_accessed=(qs.size + do.size) * qs.dtype.itemsize
            + h * (k.size + v.size) // hkv * k.dtype.itemsize
            + (h * m_pad * d + h * n_pad * (d + dv)) * 4,
            transcendentals=h * n_pad * (band_i * block_q),
        ),
        interpret=interpret,
    )(offsets, lse_rep, delta_rep, qs, k, v, do, *seg_inputs)
    if group > 1:
        dkp = dkp.reshape(hkv, group, n_pad, d).sum(axis=1)
        dvp = dvp.reshape(hkv, group, n_pad, dv).sum(axis=1)
    return dq, dkp, dvp


def _sink_patch(q, k, v, out, lse, dout, *, scale, window, sinks, softcap,
                q_offset=None, kv_valid=None):
    """Gradient contributions of sink pairs OUTSIDE the window band.

    The visible set of a windowed+sinks forward partitions exactly into
    window pairs (col within the last `window` positions — covered by
    the banded Pallas kernels with their window-only mask) and sink
    pairs past the window (col < sinks and col < row - (window-1) —
    covered here).  P is recomputed from the saved lse exactly like the
    kernels (same pre-scaled, re-rounded Q; see `flash.py::_flash_call`),
    so each pair is counted once with the forward's probabilities.  The
    sliver is (m x sinks<=window start) — O(m·sinks·d) FLOPs, a few
    fused XLA einsums; no Pallas variant needed.

    ``q_offset`` (dynamic) gives the global position of local Q row 0 —
    sinks under context parallelism, where the caller holds a Q shard
    against full local KV (kv_offset must be 0: sink rows are absolute
    positions of THIS call's KV); ``kv_valid`` masks a padded KV tail.
    """
    h, m, d = q.shape
    hkv, n, dv = v.shape
    group = h // hkv
    se = min(sinks, n)
    kx = _gqa_repeat(k[:, :se], group)
    vx = _gqa_repeat(v[:, :se], group)
    q32 = q.astype(jnp.float32)
    k32 = kx.astype(jnp.float32)
    do32 = dout.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), -1)  # (h, m)
    qsi = (q32 * (scale * _LOG2E)).astype(q.dtype).astype(jnp.float32)
    s = jnp.einsum("hmd,hsd->hms", qsi, k32) * _LN2
    dcap = None
    if softcap is not None:
        t = jnp.tanh(s / softcap)
        s = softcap * t
        dcap = 1.0 - t * t
    lse32 = lse.astype(jnp.float32)[..., None]
    rows = jnp.arange(m) + (0 if q_offset is None else q_offset)
    mask = (jnp.arange(se)[None, :] < rows[:, None] - (window - 1))[None]
    if kv_valid is not None:
        mask = jnp.logical_and(mask,
                               (jnp.arange(se) < kv_valid)[None, None, :])
    mask = jnp.logical_and(mask, lse32 != NEG_INF)
    p = jnp.where(mask, jnp.exp(s - jnp.where(mask, lse32, 0.0)), 0.0)
    dp = jnp.einsum("hme,hse->hms", do32, vx.astype(jnp.float32))
    ds = p * (dp - delta[..., None])
    if dcap is not None:
        ds = ds * dcap
    dq_s = jnp.einsum("hms,hsd->hmd", ds, k32) * scale
    dk_s = jnp.einsum("hms,hmd->hsd", ds, q32) * scale
    dv_s = jnp.einsum("hms,hme->hse", p, do32)
    if group > 1:
        dk_s = dk_s.reshape(hkv, group, se, d).sum(axis=1)
        dv_s = dv_s.reshape(hkv, group, se, dv).sum(axis=1)
    return dq_s, dk_s, dv_s, se


def _gqa_repeat(x, group):
    return jnp.repeat(x, group, axis=0) if group > 1 else x


def _tuned_bwd_tiles(kernel: str, d: int, dtype, window, m, n):
    """Tuning-table tiles for a backward family, or None (heuristic).
    Skipped when the caller has no shape (``m`` None — the defaults are
    also exercised shape-free by tests and docs)."""
    if m is None:
        return None
    try:
        from attention_tpu.tuning.lookup import key_fields, lookup

        entry = lookup(kernel, dtype=dtype,
                       **key_fields(kernel, seq=m, dim=d, window=window))
    except Exception:  # noqa: BLE001 - tuning must never break dispatch
        return None
    if entry is None:
        return None
    try:
        bq, bk = int(entry["block_q"]), int(entry["block_k"])
    except (KeyError, TypeError, ValueError):
        return None
    if bq <= 0 or bk <= 0 or bq % 128 or bk % 128:
        return None
    return BlockSizes(min(bq, _ceil_to(m, 128)),
                      min(bk, _ceil_to(n if n is not None else m, 128)))


def default_bwd_block_sizes(d: int, dtype, window, *,
                            m: int | None = None,
                            n: int | None = None) -> BlockSizes:
    """Measured backward tile defaults (see the rationale comment at the
    use site in :func:`flash_backward`), behind a tuning-table lookup
    (`attention_tpu.tuning`; a host with no cache entries resolves to
    the heuristic below unchanged).  Windowed shapes keep the
    round-1 512x512 — the banded grid covers
    ceil((window-1+block_q)/block_k)+1 KV blocks, so a taller tile
    computes more masked band columns; confirmed by a device-clock
    sweep at w=1024 seq=32k: 512x512 = 3.96 ms vs 4.10-6.23 for every
    other tile tried."""
    import jax.numpy as _jnp

    tuned = _tuned_bwd_tiles("flash_bwd", d, dtype, window, m, n)
    if tuned is not None:
        return tuned
    if window is not None or d > 128:
        return BlockSizes(512, 512)
    if _jnp.dtype(dtype).itemsize <= 2:
        return BlockSizes(1024, 1024)
    return BlockSizes(512, 1024)


def default_fused_bwd_block_sizes(d: int, dtype,
                                  window=None, *,
                                  m: int | None = None,
                                  n: int | None = None) -> BlockSizes:
    """Tile defaults for the fused single-pass backward kernel (swept
    separately from the two-kernel path: the fused kernel's VMEM also
    holds the per-head (m_pad, d) fp32 dQ block, so its tile budget is
    tighter).  Device-clock sweep on the real v5e chip: a wide
    **512x4096** wins every shape tried — 32k single-head 10.32 ms (vs
    10.66 for 1024x1024, 10.49 for 512x2048), 32k causal 6.17, GQA
    8q/2kv 32k causal 51.2 (vs 55.9), fp32 4h/8k 3.10 (vs 3.19 for the
    old 512x1024); 512x8192 and 1024x4096 fail to compile (VMEM).
    Windowed shapes take a compact square: executed band columns per q
    row scale with (window + block_q + block_k), so small tiles waste
    the least band (the same argument as the two-kernel windowed
    default).  Swept at seq=32k: 512x512 wins w=1024 (0.977 ms vs
    1.068 for 512x1024) and w=256 (0.707, tied with 256x256's 0.705),
    and sits 2% off 1024x1024 at w=4096 (2.028 vs 1.987) — one default
    within 2% of best across the window range beats a size ladder.
    Like :func:`default_bwd_block_sizes`, a tuning-table entry (user
    cache -> shipped table) overrides the heuristic; note tuned fused
    tiles still pass through `_fused_plan`'s VMEM feasibility check, so
    an oversized entry demotes the call rather than failing compile."""
    tuned = _tuned_bwd_tiles("flash_bwd_fused", d, dtype, window, m, n)
    if tuned is not None:
        return tuned
    if window is not None:
        return BlockSizes(512, 512)
    return BlockSizes(512, 4096)


def flash_backward(
    q: jax.Array,  # (h, m, d)
    k: jax.Array,  # (hkv, n, d)
    v: jax.Array,  # (hkv, n, dv)
    out: jax.Array,  # (h, m, dv)
    lse: jax.Array,  # (h, m), natural-log domain
    dout: jax.Array,  # (h, m, dv)
    *,
    scale: float,
    causal: bool = False,
    block_sizes: BlockSizes | None = None,
    interpret: bool = False,
    q_segment_ids=None,
    kv_segment_ids=None,
    window: int | None = None,
    softcap: float | None = None,
    sinks: int | None = None,
    q_offset=None,
    kv_offset=None,
    kv_valid=None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """dQ, dK, dV via the two Pallas backward kernels.

    ``softcap`` must match the forward's: P is recomputed from capped
    scores and dS picks up the 1 - tanh^2 chain factor.  ``sinks``
    (StreamingLLM, requires ``window``) adds the out-of-window sink
    pairs via the XLA sliver `_sink_patch` on top of the banded
    window-masked kernels.

    ``q_offset``/``kv_offset``/``kv_valid`` are dynamic scalars with the
    same contract as the forward kernel's (`flash.py::_flash_call`): the
    global sequence positions of local row 0 and the count of valid
    local KV rows — what makes the backward composable under context
    parallelism (each device differentiates its shard of the reference's
    orchestrated distribution, `attention-mpi.c:191-407`).  ``sinks``
    pins ABSOLUTE positions and is not supported together with offsets.
    """
    if sinks is not None and kv_offset is not None:
        raise ValueError(
            "sinks do not compose with kv_offset (sink positions are "
            "absolute positions of THIS call's KV rows — a shifted KV "
            "shard cannot contain them); q_offset/kv_valid are fine"
        )
    segmented = q_segment_ids is not None
    if segmented != (kv_segment_ids is not None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires causal=True")
    if sinks is not None:
        if window is None:
            raise ValueError("sinks require window= (see flash_attention)")
        if segmented:
            raise ValueError("sinks do not compose with segment_ids")
    # Backward default pinned independently of the forward's: with the
    # deterministic device clock (scripts/bwd_sweep.py + the shape grid
    # in RESULTS.md round 2), 1024x1024 beats the round-1 512x512 by
    # 22-28% on bf16 at every shape that compiles (9.43->7.39 ms at
    # 16q/4kv 8k causal; 8.24->6.41 at 16k; 6.50->5.40 non-causal 8k),
    # where 2048x1024 / 1024x2048 VMEM-OOM on some shapes.  fp32 inputs
    # double the q/k/v/dO tile bytes and 1024x1024 OOMs inside the full
    # VJP module (16.79M vs the 16M scoped limit at 16q/4kv 8k, even
    # though it compiles standalone), so fp32 takes 512x1024 (still 15%
    # over the old default: 8.98 vs 10.60 ms).  Larger head dims keep
    # the smallest footprint.
    h, m, d = q.shape
    hkv, n, dv = v.shape
    group = h // hkv

    # Long sequences exceed the fused kernel's resident-dQ budget as a
    # WHOLE but not per Q-row chunk — the context-parallel decomposition
    # applied locally: run the fused kernel per chunk with the chunk's
    # global q_offset and sum the dK/dV contributions (exactly what the
    # CP orchestrator does across devices, `parallel/cp.py`).  10·mnd
    # executed FLOPs instead of the two-kernel fallback's 14·mnd at
    # 131k.  Chunk rounding to bf16 before the sum matches the CP
    # path's per-shard precision (each shard's dK/dV are cast before
    # the psum there too).
    chunk = (None if _FORCE_TWO_KERNEL else
             _fused_chunk_choice(m, n, d, dv, block_sizes, q.dtype,
                                 window=window, segmented=segmented))
    if chunk is not None:
        base_off = 0 if q_offset is None else q_offset
        dq_parts = []
        dk32 = dv32 = None
        for s0 in range(0, m, chunk):
            e0 = min(m, s0 + chunk)
            off = (base_off + s0
                   if causal or q_offset is not None else None)
            dq_c, dk_c, dv_c = flash_backward(
                q[:, s0:e0], k, v, out[:, s0:e0], lse[:, s0:e0],
                dout[:, s0:e0], scale=scale, causal=causal,
                window=window, softcap=softcap, sinks=sinks,
                interpret=interpret, q_offset=off,
                kv_offset=kv_offset, kv_valid=kv_valid,
            )
            dq_parts.append(dq_c)
            dk_c = dk_c.astype(jnp.float32)
            dv_c = dv_c.astype(jnp.float32)
            dk32 = dk_c if dk32 is None else dk32 + dk_c
            dv32 = dv_c if dv32 is None else dv32 + dv_c
        return (jnp.concatenate(dq_parts, axis=1),
                dk32.astype(k.dtype), dv32.astype(v.dtype))

    use_fused = not _FORCE_TWO_KERNEL and fused_backward_applicable(
        m, d, window=window, sinks=sinks, segmented=segmented,
        n=n, dv=dv, block_sizes=block_sizes, dtype=q.dtype)
    if use_fused:
        bs = _fused_plan(m, n, d, dv, block_sizes, q.dtype, window)
    elif block_sizes is not None:
        bs = block_sizes
    else:
        bs = default_bwd_block_sizes(q.shape[-1], q.dtype, window,
                                     m=m, n=n)

    # Same pre-scaled (and re-rounded) Q the forward kernel saw, so the
    # recomputed P matches the forward probabilities bit-for-bit modulo
    # fp32 non-associativity.
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    lse2 = lse.astype(jnp.float32) * _LOG2E
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), -1)

    block_q = min(bs.block_q, _ceil_to(m, 128))
    block_k = min(bs.block_k, _ceil_to(n, 128))
    m_pad = _ceil_to(m, block_q)
    n_pad = _ceil_to(n, block_k)
    do32 = dout.astype(jnp.float32)
    if m_pad != m:
        # Padded Q rows are zero ⇒ their scores are 0 and (with lse2
        # padded to 0) P = 1, but dO = D = 0 zeroes every contribution.
        qs = jnp.pad(qs, ((0, 0), (0, m_pad - m), (0, 0)))
        do32 = jnp.pad(do32, ((0, 0), (0, m_pad - m), (0, 0)))
        lse2 = jnp.pad(lse2, ((0, 0), (0, m_pad - m)))
        delta = jnp.pad(delta, ((0, 0), (0, m_pad - m)))
    if n_pad != n:
        # Padded K/V rows are zero ⇒ they null dQ contributions (dS K
        # hits zero K rows); their dK/dV rows are sliced away below.
        k = jnp.pad(k, ((0, 0), (0, n_pad - n), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, n_pad - n), (0, 0)))
    do = do32.astype(q.dtype)
    compute_dtype = q.dtype

    # Stats enter lane-replicated — Mosaic's block tiling needs the last
    # two dims (8k, 128m), which a (1, block_q) row block violates.
    lse_rep = jnp.broadcast_to(lse2[..., None], (h, m_pad, _STAT_LANES))
    delta_rep = jnp.broadcast_to(delta[..., None], (h, m_pad, _STAT_LANES))

    num_i = m_pad // block_q
    num_j = n_pad // block_k
    if window is None:
        band_j = num_j
        band_i = num_i
    else:
        # banded grids: the inner sweep covers only blocks the window
        # can touch (see the forward kernel's banded-grid note)
        band_j = min(num_j, -(-(window - 1 + block_q) // block_k) + 1)
        band_i = min(num_i, (block_k - 1 + window - 1) // block_q + 2)

    dynamic_valid = kv_valid is not None
    offsets = jnp.stack(
        [
            jnp.asarray(0 if q_offset is None else q_offset, jnp.int32),
            jnp.asarray(0 if kv_offset is None else kv_offset, jnp.int32),
            jnp.asarray(n if kv_valid is None else kv_valid, jnp.int32),
        ]
    )

    if use_fused:
        # single-pass fused kernel: 10·mnd executed backward FLOPs vs the
        # two-kernel path's 14·mnd (S and dO·Vᵀ computed once, not twice)
        fused_seg = ()
        if segmented:
            from attention_tpu.ops.flash import segment_masks

            fused_seg = segment_masks(q_segment_ids, kv_segment_ids,
                                      m, n, m_pad, n_pad)
        dq_f, dk_f, dv_f = _fused_backward(
            qs, k, v, lse_rep, delta_rep, do, offsets,
            h=h, hkv=hkv, m_pad=m_pad, n_pad=n_pad, d=d, dv=dv,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            softcap=softcap, dynamic_valid=dynamic_valid,
            interpret=interpret, window=window, seg_inputs=fused_seg)
        dq_f = dq_f[:, :m]
        dk_f, dv_f = dk_f[:, :n], dv_f[:, :n]
        if sinks is not None:
            # out-of-window sink pairs, same sliver as the two-kernel
            # composition (the banded fused kernel covers the window
            # band only)
            dq_s, dk_s, dv_s, se = _sink_patch(
                q, k[:, :n], v[:, :n], out, lse, dout,
                scale=scale, window=window, sinks=sinks, softcap=softcap,
                q_offset=q_offset, kv_valid=kv_valid,
            )
            dq_f = dq_f + dq_s
            dk_f = dk_f.at[:, :se].add(dk_s)
            dv_f = dv_f.at[:, :se].add(dv_s)
        return (dq_f.astype(q.dtype), dk_f.astype(k.dtype),
                dv_f.astype(v.dtype))

    def j_abs(ii, jj, off):
        # clamp band-tail steps to the last block the row actually
        # computes (its causal diagonal), so their DMAs elide instead of
        # fetching a never-used block
        if window is None:
            jj_c = jj
        else:
            base = jnp.maximum(
                (ii * block_q + off[0] - off[1] - (window - 1)) // block_k,
                0,
            )
            causal_last = jnp.maximum(
                (ii * block_q + block_q - 1 + off[0] - off[1]) // block_k, 0
            )
            jj_c = jnp.minimum(base + jj,
                               jnp.minimum(causal_last, num_j - 1))
        if dynamic_valid:
            valid_last = jnp.maximum(
                (off[2] + block_k - 1) // block_k - 1, 0
            )
            jj_c = jnp.minimum(jj_c, valid_last)
        return jj_c

    def i_abs(jj, ii, off):
        # clamp to the last q block inside this kv block's window span
        if window is None:
            return ii
        first = jnp.maximum(
            (jj * block_k + off[1] - off[0]) // block_q, 0
        )
        win_last = jnp.maximum(
            (jj * block_k + block_k - 1 + window - 1 + off[1] - off[0])
            // block_q,
            0,
        )
        return jnp.minimum(first + ii,
                           jnp.minimum(win_last, num_i - 1))

    seg_inputs = ()
    seg_specs_q = []
    seg_specs_kv = []
    if segmented:
        from attention_tpu.ops.flash import segment_masks

        q_rep, kv_rep = segment_masks(q_segment_ids, kv_segment_ids,
                                      m, n, m_pad, n_pad)
        seg_inputs = (q_rep, kv_rep)
        seg_specs_q = [
            pl.BlockSpec((block_q, _STAT_LANES),
                         lambda hh, ii, jj, off: (ii, 0)),
            pl.BlockSpec((8, block_k),
                         lambda hh, ii, jj, off: (0, j_abs(ii, jj, off))),
        ]
        seg_specs_kv = [
            pl.BlockSpec((block_q, _STAT_LANES),
                         lambda jj, hh, ii, off: (i_abs(jj, ii, off), 0)),
            pl.BlockSpec((8, block_k), lambda jj, hh, ii, off: (0, jj)),
        ]

    stat_spec_q = pl.BlockSpec(
        (1, block_q, _STAT_LANES), lambda hh, ii, jj, off: (hh, ii, 0)
    )
    dq_grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, num_i, band_j),
        in_specs=[
            stat_spec_q,
            stat_spec_q,
            pl.BlockSpec((1, block_q, d),
                         lambda hh, ii, jj, off: (hh, ii, 0)),
            pl.BlockSpec(
                (1, block_k, d),
                lambda hh, ii, jj, off: (hh // group, j_abs(ii, jj, off), 0),
            ),
            pl.BlockSpec(
                (1, block_k, dv),
                lambda hh, ii, jj, off: (hh // group, j_abs(ii, jj, off), 0),
            ),
            pl.BlockSpec((1, block_q, dv),
                         lambda hh, ii, jj, off: (hh, ii, 0)),
            *seg_specs_q,
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda hh, ii, jj, off: (hh, ii, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            scale=scale,
            out_dtype=q.dtype,
            compute_dtype=compute_dtype,
            segmented=segmented,
            window=window,
            n_j_total=num_j,
            softcap2=None if softcap is None else softcap * _LOG2E,
            dynamic_valid=dynamic_valid,
        ),
        grid_spec=dq_grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, m_pad, d), q.dtype),
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=110 * 2**20),
        cost_estimate=pl.CostEstimate(
            flops=6 * h * m_pad * (band_j * block_k) * d,
            bytes_accessed=(qs.size + do.size) * qs.dtype.itemsize
            + h * (k.size + v.size) // hkv * k.dtype.itemsize
            + h * m_pad * d * qs.dtype.itemsize,
            transcendentals=h * m_pad * (band_j * block_k),
        ),
        interpret=interpret,
    )(offsets, lse_rep, delta_rep, qs, k, v, do, *seg_inputs)[:, :m]

    stat_spec_kv = pl.BlockSpec(
        (1, block_q, _STAT_LANES),
        lambda jj, hh, ii, off: (hh, i_abs(jj, ii, off), 0),
    )
    dkv_grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_j, h, band_i),
        in_specs=[
            stat_spec_kv,
            stat_spec_kv,
            pl.BlockSpec((1, block_q, d),
                         lambda jj, hh, ii, off: (hh, i_abs(jj, ii, off), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda jj, hh, ii, off: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_k, dv),
                         lambda jj, hh, ii, off: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_q, dv),
                         lambda jj, hh, ii, off: (hh, i_abs(jj, ii, off), 0)),
            *seg_specs_kv,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d),
                         lambda jj, hh, ii, off: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_k, dv),
                         lambda jj, hh, ii, off: (hh // group, jj, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, dv), jnp.float32),
        ],
    )
    dk, dvg = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            group=group,
            compute_dtype=compute_dtype,
            segmented=segmented,
            window=window,
            n_i_total=num_i,
            softcap2=None if softcap is None else softcap * _LOG2E,
            dynamic_valid=dynamic_valid,
        ),
        grid_spec=dkv_grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hkv, n_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((hkv, n_pad, dv), jnp.float32),
        ],
        compiler_params=_compiler_params(
            ("parallel", "arbitrary", "arbitrary"),
            vmem_limit_bytes=110 * 2**20),
        cost_estimate=pl.CostEstimate(
            flops=8 * h * (band_i * block_q) * n_pad * d,
            bytes_accessed=(qs.size + do.size) * qs.dtype.itemsize
            + h * (k.size + v.size) // hkv * k.dtype.itemsize
            + (n_pad * (d + dv)) * hkv * 4,
            transcendentals=h * (band_i * block_q) * n_pad,
        ),
        interpret=interpret,
    )(offsets, lse_rep, delta_rep, qs, k, v, do, *seg_inputs)
    dk, dvg = dk[:, :n], dvg[:, :n]
    if sinks is not None:
        dq_s, dk_s, dv_s, se = _sink_patch(
            q, k[:, :n], v[:, :n], out, lse, dout,
            scale=scale, window=window, sinks=sinks, softcap=softcap,
            q_offset=q_offset, kv_valid=kv_valid,
        )
        dq = (dq.astype(jnp.float32) + dq_s).astype(q.dtype)
        dk = dk.at[:, :se].add(dk_s)
        dvg = dvg.at[:, :se].add(dv_s)
    return dq, dk.astype(k.dtype), dvg.astype(v.dtype)
