"""Pallas flash-attention backward kernels for TPU.

The reference is forward-only (no backward exists in `attention.c` /
`attention-mpi.c`); this is new training surface.  The math is the
standard flash backward — recompute P tile-wise from the saved
log-sum-exp, then

    P  = exp(S - lse)            D  = rowsum(dO ∘ O)   (precomputed)
    dV = Pᵀ dO                   dS = P ∘ (dO Vᵀ - D)
    dQ = scale · dS K            dK = scale · dSᵀ Q

— executed as two Pallas kernels instead of blocked XLA einsums:

  * **dQ kernel**: grid (head, q-block, kv-block), kv innermost; dQ
    accumulates in VMEM scratch across the KV sweep (the mirror of the
    forward's online accumulator).
  * **dK/dV kernel**: grid (kv-block, q-head, q-block) with the q-head
    dimension ordered so all Q heads sharing one KV head (GQA) form a
    contiguous run — dK/dV accumulate across the whole run in VMEM
    scratch and are written once per KV head.  The grouped reduction
    never materializes `jnp.repeat`-expanded gradients in HBM.

Everything runs **KV-major** (tiles are (block_k, block_q)): the per-row
stats lse/D then broadcast along lanes as natural (1, block_q) row
vectors, so no in-kernel transposes of narrow tiles are needed; the MXU
does not care about the orientation of the contractions.

Domain bookkeeping matches the forward (`flash.py::_flash_call`): Q is
pre-scaled by scale·log2(e) and re-rounded to the input dtype, so scores
are log2-domain and P = exp2(S₂ - lse₂) reproduces the forward's exact
probabilities; dK picks up a ln2 factor (dK = ln2 · dSᵀ Q_scaled) and dQ
the plain `scale` (contraction against unscaled K).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from attention_tpu.ops.flash import (
    _LN2,
    _LOG2E,
    NEG_INF,
    BlockSizes,
    _ceil_to,
    _compiler_params,
)


def _recompute_p_t(qs, k, lse_row, *, causal, q_base, k_base):
    """(block_k, block_q) probability tile, KV-major.

    ``qs`` is the forward's pre-scaled Q (scores come out log2-domain),
    ``lse_row`` a (1, block_q) log2-domain log-sum-exp row vector.
    """
    s2t = jax.lax.dot_general(
        k, qs, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (block_k, block_q)
    p_t = jnp.exp2(s2t - lse_row)
    if causal:
        col = k_base + jax.lax.broadcasted_iota(jnp.int32, p_t.shape, 0)
        row = q_base + jax.lax.broadcasted_iota(jnp.int32, p_t.shape, 1)
        # also guards rows the forward fully masked (lse == -inf)
        p_t = jnp.where(jnp.logical_and(col <= row, lse_row != NEG_INF),
                        p_t, 0.0)
    return p_t


def _dq_kernel(
    lse_ref, delta_ref, qs_ref, k_ref, v_ref, do_ref, dq_ref, acc_scr,
    *, causal, block_q, block_k, scale, out_dtype, compute_dtype,
):
    j = pl.program_id(2)
    q_base = pl.program_id(1) * block_q
    k_base = j * block_k

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        qs, k, v, do = qs_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p_t = _recompute_p_t(
            qs, k, lse_ref[...], causal=causal, q_base=q_base, k_base=k_base
        )
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, block_q) = (dO Vᵀ)ᵀ
        ds_t = p_t * (dp_t - delta_ref[...])
        acc_scr[...] += jax.lax.dot_general(
            ds_t.astype(compute_dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, d) = dS K

    if causal:
        # KV tiles strictly above the diagonal are all zeros under the
        # causal mask — skip them (halves causal backward FLOPs).
        # Init/finalize stay outside the guard.
        pl.when(k_base <= q_base + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = (acc_scr[...] * scale).astype(out_dtype)


def _dkv_kernel(
    lse_ref, delta_ref, qs_ref, k_ref, v_ref, do_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, causal, block_q, block_k, group, compute_dtype,
):
    h = pl.program_id(1)
    i = pl.program_id(2)
    h_in_group = jax.lax.rem(h, group)
    q_base = i * block_q
    k_base = pl.program_id(0) * block_k

    @pl.when(jnp.logical_and(h_in_group == 0, i == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        qs, k, v, do = qs_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p_t = _recompute_p_t(
            qs, k, lse_ref[...], causal=causal, q_base=q_base, k_base=k_base
        )
        dv_scr[...] += jax.lax.dot_general(
            p_t.astype(compute_dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, dv) = Pᵀ dO
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta_ref[...])
        dk_scr[...] += jax.lax.dot_general(
            ds_t.astype(compute_dtype), qs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, d) = dSᵀ Q_scaled

    if causal:
        # Q tiles wholly above the diagonal contribute nothing to this
        # KV block — skip them (halves causal backward FLOPs).
        pl.when(k_base <= q_base + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(
        jnp.logical_and(
            h_in_group == group - 1, i == pl.num_programs(2) - 1
        )
    )
    def _finalize():
        # Q_scaled carries scale·log2(e); ln2 restores the plain `scale`.
        dk_ref[0] = dk_scr[...] * _LN2
        dv_ref[0] = dv_scr[...]


def flash_backward(
    q: jax.Array,  # (h, m, d)
    k: jax.Array,  # (hkv, n, d)
    v: jax.Array,  # (hkv, n, dv)
    out: jax.Array,  # (h, m, dv)
    lse: jax.Array,  # (h, m), natural-log domain
    dout: jax.Array,  # (h, m, dv)
    *,
    scale: float,
    causal: bool = False,
    block_sizes: BlockSizes | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """dQ, dK, dV via the two Pallas backward kernels."""
    # Backward default pinned independently of the forward's: the
    # forward retune to (256, 1024) (scripts/kernel_sweep.py) measured
    # only the forward kernel; the KV-major backward tiles have their
    # own VMEM footprint (fp32 P/dS tiles, two accumulators).
    bs = block_sizes or BlockSizes(256, 512)
    h, m, d = q.shape
    hkv, n, dv = v.shape
    group = h // hkv

    # Same pre-scaled (and re-rounded) Q the forward kernel saw, so the
    # recomputed P matches the forward probabilities bit-for-bit modulo
    # fp32 non-associativity.
    qs = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    lse2 = (lse.astype(jnp.float32) * _LOG2E)
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), -1)

    block_q = min(bs.block_q, _ceil_to(m, 128))
    block_k = min(bs.block_k, _ceil_to(n, 128))
    m_pad = _ceil_to(m, block_q)
    n_pad = _ceil_to(n, block_k)
    do32 = dout.astype(jnp.float32)
    if m_pad != m:
        # Padded Q rows are zero ⇒ their scores are 0 and (with lse2
        # padded to 0) P = 1, but dO = D = 0 zeroes every contribution.
        qs = jnp.pad(qs, ((0, 0), (0, m_pad - m), (0, 0)))
        do32 = jnp.pad(do32, ((0, 0), (0, m_pad - m), (0, 0)))
        lse2 = jnp.pad(lse2, ((0, 0), (0, m_pad - m)))
        delta = jnp.pad(delta, ((0, 0), (0, m_pad - m)))
    if n_pad != n:
        # Padded K/V rows are zero ⇒ they null dQ contributions (dS K
        # hits zero K rows); their dK/dV rows are sliced away below.
        k = jnp.pad(k, ((0, 0), (0, n_pad - n), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, n_pad - n), (0, 0)))
    do = do32.astype(q.dtype)
    compute_dtype = q.dtype

    num_i = m_pad // block_q
    num_j = n_pad // block_k

    stat_spec_q_major = pl.BlockSpec((1, block_q), lambda hh, ii, jj: (hh, ii))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            scale=scale,
            out_dtype=q.dtype,
            compute_dtype=compute_dtype,
        ),
        grid=(h, num_i, num_j),
        in_specs=[
            stat_spec_q_major,
            stat_spec_q_major,
            pl.BlockSpec((1, block_q, d), lambda hh, ii, jj: (hh, ii, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, ii, jj: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_k, dv), lambda hh, ii, jj: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_q, dv), lambda hh, ii, jj: (hh, ii, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda hh, ii, jj: (hh, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((h, m_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=6 * h * m_pad * n_pad * d,
            bytes_accessed=(qs.size + do.size) * qs.dtype.itemsize
            + h * (k.size + v.size) // hkv * k.dtype.itemsize
            + h * m_pad * d * qs.dtype.itemsize,
            transcendentals=h * m_pad * n_pad,
        ),
        interpret=interpret,
    )(lse2, delta, qs, k, v, do)[:, :m]

    stat_spec_kv_major = pl.BlockSpec((1, block_q), lambda jj, hh, ii: (hh, ii))
    dk, dvg = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            group=group,
            compute_dtype=compute_dtype,
        ),
        grid=(num_j, h, num_i),
        in_specs=[
            stat_spec_kv_major,
            stat_spec_kv_major,
            pl.BlockSpec((1, block_q, d), lambda jj, hh, ii: (hh, ii, 0)),
            pl.BlockSpec((1, block_k, d), lambda jj, hh, ii: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_k, dv), lambda jj, hh, ii: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_q, dv), lambda jj, hh, ii: (hh, ii, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda jj, hh, ii: (hh // group, jj, 0)),
            pl.BlockSpec((1, block_k, dv), lambda jj, hh, ii: (hh // group, jj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((hkv, n_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((hkv, n_pad, dv), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, dv), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "arbitrary", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=8 * h * m_pad * n_pad * d,
            bytes_accessed=(qs.size + do.size) * qs.dtype.itemsize
            + h * (k.size + v.size) // hkv * k.dtype.itemsize
            + (n_pad * (d + dv)) * hkv * 4,
            transcendentals=h * m_pad * n_pad,
        ),
        interpret=interpret,
    )(lse2, delta, qs, k, v, do)
    return dq, dk[:, :n].astype(k.dtype), dvg[:, :n].astype(v.dtype)
