"""Rotary position embeddings (RoPE).

The reference kernel is position-free (plain SDPA over given Q/K/V —
`attention.c:20-75`); a usable model family needs positions.  RoPE is
the TPU-friendly choice: a pure elementwise rotation of Q and K that
fuses into the surrounding projections under XLA, adds no parameters,
no attention-bias tensor, and keys can be cached *already rotated* (the
score depends only on relative position), so the decode path needs no
re-rotation of history.

Split-half convention (as in the original RoFormer paper and most JAX
implementations): the head dim is split into two halves that form the
(real, imag) components of dh/2 complex pairs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, head_dim: int,
                theta: float = 10000.0) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables for ``positions`` (any shape), fp32.

    Returns arrays of shape ``positions.shape + (head_dim // 2,)``.
    """
    if head_dim % 2:
        raise ValueError(f"RoPE requires an even head_dim, got {head_dim}")
    half = head_dim // 2
    inv_freq = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotate ``x`` (..., S, dh) by its per-row positions (..., S).

    ``positions`` broadcasts against x's leading axes (pass ``(S,)`` for
    shared positions, ``(B, 1, S)``-shaped for per-sequence offsets).
    Math runs in fp32; the result is cast back to ``x.dtype``.
    """
    half = x.shape[-1] // 2
    cos, sin = rope_angles(positions, x.shape[-1], theta)
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)
