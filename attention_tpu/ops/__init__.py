from attention_tpu.ops.reference import attention_xla  # noqa: F401
from attention_tpu.ops.flash import flash_attention, flash_attention_partials  # noqa: F401
from attention_tpu.ops.decode import flash_decode  # noqa: F401
from attention_tpu.ops.quant import (  # noqa: F401
    Int4KV,
    Int4TokKV,
    QuantizedKV,
    flash_decode_int4,
    flash_decode_int4_tok,
    flash_decode_quantized,
    quantize_kv,
    quantize_kv_int4,
    quantize_kv_int4_tok,
    update_quantized_kv,
)
from attention_tpu.ops.paged import (  # noqa: F401
    OutOfPagesError,
    PageAccountingError,
    PagedKV,
    PagePool,
    paged_append,
    paged_append_chunk,
    paged_flash_decode,
    paged_fork,
    paged_from_dense,
)
from attention_tpu.ops.rope import apply_rope, rope_angles  # noqa: F401
