"""Differentiable flash attention: custom VJP around the Pallas kernel.

The reference is a forward-only inference kernel (no backward pass exists
anywhere in `attention.c`/`attention-mpi.c`); training support is new
surface this framework adds so the attention op can sit inside a model.

Design: the forward pass runs the fused Pallas kernel and saves only
(q, k, v, out, lse) — the flash-attention residual contract — instead of
the O(m·n) probability matrix.  The backward pass recomputes P tile-wise
from the saved log-sum-exp and contracts with standard flash-backward
algebra:

    P  = exp(S - lse)            D  = rowsum(dO ∘ O)
    dV = Pᵀ dO                   dS = P ∘ (dO Vᵀ - D)
    dQ = scale · dS K            dK = scale · dSᵀ Q

Backward has two interchangeable implementations:

  * ``bwd_impl="pallas"`` (default) — the two Pallas kernels in
    :mod:`attention_tpu.ops.flash_bwd` (dQ kernel + grouped dK/dV
    kernel), tiled for the MXU with VMEM scratch accumulators.
  * ``bwd_impl="xla"`` — blocked XLA einsums (``lax.map`` over Q
    chunks); memory stays O(m·chunk + chunk·n).  Kept as the
    cross-check oracle for the Pallas kernels and as a fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from attention_tpu.ops.flash import (
    _LN2,
    _LOG2E,
    BlockSizes,
    flash_attention_partials,
)

NEG_INF = float("-inf")


def _gqa_expand(k, group):
    return jnp.repeat(k, group, axis=0) if group > 1 else k


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(8, 9, 10, 11, 12, 13, 14, 15, 16))
def _flash_diff(q, k, v, q_seg, kv_seg, q_off, kv_off, kv_val, scale,
                causal, block_sizes, bwd_chunk, bwd_impl, window, softcap,
                sinks, max_mode):
    out, _ = _flash_fwd_impl(q, k, v, scale, causal, block_sizes,
                             q_seg, kv_seg, window, softcap, sinks,
                             q_off, kv_off, kv_val, max_mode)
    return out


def _seg_zeros(seg):
    """float0 cotangent for an integer segment-id primal (None stays
    None — an empty pytree's cotangent)."""
    import numpy as np

    if seg is None:
        return None
    return np.zeros(jnp.shape(seg), jax.dtypes.float0)


def _flash_fwd_impl(q, k, v, scale, causal, block_sizes, q_seg=None,
                    kv_seg=None, window=None, softcap=None, sinks=None,
                    q_off=None, kv_off=None, kv_val=None,
                    max_mode="online"):
    out_un, row_max, row_sum = flash_attention_partials(
        q, k, v, scale=scale, causal=causal, block_sizes=block_sizes,
        q_segment_ids=q_seg, kv_segment_ids=kv_seg, window=window,
        softcap=softcap, sinks=sinks,
        q_offset=q_off, kv_offset=kv_off, kv_valid=kv_val,
        max_mode=max_mode,
    )
    l_safe = jnp.where(row_sum == 0.0, 1.0, row_sum)
    out = (out_un / l_safe[..., None]).astype(q.dtype)
    lse = jnp.where(
        row_max == NEG_INF, NEG_INF, row_max + jnp.log(l_safe)
    )
    return out, lse


def _flash_diff_fwd(q, k, v, q_seg, kv_seg, q_off, kv_off, kv_val, scale,
                    causal, block_sizes, bwd_chunk, bwd_impl, window,
                    softcap, sinks, max_mode):
    out, lse = _flash_fwd_impl(q, k, v, scale, causal, block_sizes,
                               q_seg, kv_seg, window, softcap, sinks,
                               q_off, kv_off, kv_val, max_mode)
    return out, (q, k, v, q_seg, kv_seg, q_off, kv_off, kv_val, out, lse)


def _flash_diff_bwd(scale, causal, block_sizes, bwd_chunk, bwd_impl,
                    window, softcap, sinks, max_mode, res, dout):
    q, k, v, q_seg, kv_seg, q_off, kv_off, kv_val, out, lse = res
    seg_cots = (_seg_zeros(q_seg), _seg_zeros(kv_seg),
                _seg_zeros(q_off), _seg_zeros(kv_off), _seg_zeros(kv_val))
    if bwd_impl == "pallas":
        from attention_tpu.ops.flash import _should_interpret
        from attention_tpu.ops.flash_bwd import flash_backward

        return flash_backward(
            q, k, v, out, lse, dout,
            scale=scale, causal=causal, block_sizes=block_sizes,
            interpret=_should_interpret(),
            q_segment_ids=q_seg, kv_segment_ids=kv_seg, window=window,
            softcap=softcap, sinks=sinks,
            q_offset=q_off, kv_offset=kv_off, kv_valid=kv_val,
        ) + seg_cots
    h, m, dk = q.shape
    hkv, n, dv = v.shape
    group = h // hkv
    kx = _gqa_expand(k, group)  # (h, n, dk)
    vx = _gqa_expand(v, group)
    qo = 0 if q_off is None else q_off
    ko = 0 if kv_off is None else kv_off

    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, kx, vx))
    dout32 = dout.astype(jnp.float32)
    out32 = out.astype(jnp.float32)
    q_dtype = q.dtype

    # D_i = sum_d dO_id * O_id  (the softmax-jacobian diagonal term)
    delta = jnp.sum(dout32 * out32, axis=-1)  # (h, m)

    chunk = min(bwd_chunk, m)
    pad = (-m) % chunk
    # segment ids: -1 on padded q rows matches no (non-negative) kv id
    qseg_arr = (jnp.full((m,), 0, jnp.int32) if q_seg is None
                else jnp.asarray(q_seg, jnp.int32))
    kvseg_arr = (jnp.full((n,), 0, jnp.int32) if kv_seg is None
                 else jnp.asarray(kv_seg, jnp.int32))
    if pad:
        qp = jnp.pad(q32, ((0, 0), (0, pad), (0, 0)))
        dop = jnp.pad(dout32, ((0, 0), (0, pad), (0, 0)))
        lsep = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=NEG_INF)
        deltap = jnp.pad(delta, ((0, 0), (0, pad)))
        qsegp = jnp.pad(qseg_arr, (0, pad), constant_values=-1)
    else:
        qp, dop, lsep, deltap, qsegp = q32, dout32, lse, delta, qseg_arr
    n_chunks = qp.shape[1] // chunk
    qc = qp.reshape(h, n_chunks, chunk, dk).transpose(1, 0, 2, 3)
    doc = dop.reshape(h, n_chunks, chunk, dv).transpose(1, 0, 2, 3)
    lsec = lsep.reshape(h, n_chunks, chunk).transpose(1, 0, 2)
    deltac = deltap.reshape(h, n_chunks, chunk).transpose(1, 0, 2)
    qsegc = qsegp.reshape(n_chunks, chunk)

    row_base = jnp.arange(n_chunks) * chunk
    segmented = q_seg is not None

    def one_chunk(args):
        qi, doi, lsei, di, base, qsegi = args  # (h, chunk, dk) etc.
        # Recompute P with the EXACT forward scores: the kernel folds
        # scale*log2(e) into Q and re-rounds to q.dtype
        # (flash.py::_flash_call), so the backward round-trips this
        # chunk's Q identically or p = exp(s - lse) drifts from the
        # forward probabilities on bf16 inputs (padded zeros round-trip
        # to zero).  Gradients still flow through the true
        # s = scale·q·k (rounding treated as identity), so dq/dk keep
        # the plain `scale` factor with the original q.
        qsi = (qi * (scale * _LOG2E)).astype(q_dtype).astype(jnp.float32)
        s = jnp.einsum("hqd,hnd->hqn", qsi, k32) * _LN2
        dcap = None
        if softcap is not None:
            t = jnp.tanh(s / softcap)
            s = softcap * t
            dcap = 1.0 - t * t
        mask = None
        if causal:
            rows = base + jnp.arange(chunk) + qo
            cols = jnp.arange(n) + ko
            mask = cols[None, :] <= rows[:, None]
            if window is not None:
                win = cols[None, :] >= rows[:, None] - (window - 1)
                if sinks is not None:
                    # pinned StreamingLLM sink positions stay visible
                    win = jnp.logical_or(win, cols[None, :] < sinks)
                mask = jnp.logical_and(mask, win)
        if kv_val is not None:
            vm = (jnp.arange(n) < kv_val)[None, :]
            mask = vm if mask is None else jnp.logical_and(mask, vm)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        if segmented:
            s = jnp.where(qsegi[:, None] == kvseg_arr[None, :], s, NEG_INF)
        p = jnp.where(lsei[..., None] == NEG_INF, 0.0, jnp.exp(s - lsei[..., None]))
        dp = jnp.einsum("hqe,hne->hqn", doi, v32)
        ds = p * (dp - di[..., None])  # (h, chunk, n)
        if dcap is not None:
            ds = ds * dcap  # chain through cap*tanh(s/cap)
        dq_i = jnp.einsum("hqn,hnd->hqd", ds, k32) * scale
        dk_i = jnp.einsum("hqn,hqd->hnd", ds, qi) * scale
        dv_i = jnp.einsum("hqn,hqe->hne", p, doi)
        return dq_i, dk_i, dv_i

    dq_chunks, dk_parts, dv_parts = lax.map(
        one_chunk, (qc, doc, lsec, deltac, row_base, qsegc)
    )
    dq = dq_chunks.transpose(1, 0, 2, 3).reshape(h, m + pad, dk)[:, :m]
    dk_full = jnp.sum(dk_parts, axis=0)  # (h, n, dk)
    dv_full = jnp.sum(dv_parts, axis=0)  # (h, n, dv)
    if group > 1:
        dk_full = dk_full.reshape(hkv, group, n, dk).sum(axis=1)
        dv_full = dv_full.reshape(hkv, group, n, dv).sum(axis=1)
    return (dq.astype(q.dtype), dk_full.astype(k.dtype),
            dv_full.astype(v.dtype)) + seg_cots


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention_diff(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float | None = None,
    causal: bool = False,
    block_sizes: BlockSizes | None = None,
    bwd_chunk: int = 512,
    bwd_impl: str = "pallas",
    q_segment_ids=None,
    kv_segment_ids=None,
    window: int | None = None,
    softcap: float | None = None,
    sinks: int | None = None,
    q_offset=None,
    kv_offset=None,
    kv_valid=None,
    max_mode: str = "online",
) -> jax.Array:
    """Differentiable fused attention; same shape contract as
    :func:`attention_tpu.ops.flash.flash_attention` (2D/3D/4D, GQA).

    Forward = Pallas flash kernel; backward = Pallas backward kernels
    (``bwd_impl="pallas"``) or the blocked-XLA recompute
    (``bwd_impl="xla"``), both from the saved log-sum-exp.  Segment ids
    ((m,)/(n,) int32, shared across heads; 2D/3D inputs only) mask
    attention across packed-sequence boundaries in both directions of
    the VJP.  ``sinks`` (StreamingLLM pinned positions; requires
    ``window``) is differentiable too: the banded backward kernels
    handle the window pairs and `flash_bwd._sink_patch` the sink
    sliver.  ``q_offset``/``kv_offset``/``kv_valid`` (dynamic int32
    scalars, same contract as :func:`flash_attention`) keep causal
    masking and valid-prefix masking correct when the caller holds only
    a sequence shard — the differentiable leg of context parallelism;
    they flow through both the forward and backward kernels.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if bwd_impl not in ("pallas", "xla"):
        raise ValueError(f"unknown bwd_impl {bwd_impl!r}")
    if sinks is not None and kv_offset is not None:
        raise ValueError(
            "sinks do not compose with kv_offset (sink positions are "
            "absolute positions of THIS call's KV rows); q_offset and "
            "kv_valid compose fine — the context-parallel case"
        )
    # None flows through: the forward resolves it via
    # BlockSizes.for_shape(returns_stats=True) and flash_backward via
    # default_bwd_block_sizes (dtype- and window-aware) — the two
    # kernels are tuned independently (see flash_bwd.py).
    bs = block_sizes
    qseg, kvseg = q_segment_ids, kv_segment_ids
    offs = tuple(
        None if o is None else jnp.asarray(o, jnp.int32)
        for o in (q_offset, kv_offset, kv_valid)
    )
    if qseg is not None and q.ndim == 4:
        raise ValueError(
            "segment ids support 2D/3D inputs (ids shared across heads)"
        )
    if q.ndim == 2:
        return _flash_diff(
            q[None], k[None], v[None], qseg, kvseg, *offs, scale, causal,
            bs, bwd_chunk, bwd_impl, window, softcap, sinks, max_mode,
        )[0]
    if q.ndim == 3:
        return _flash_diff(q, k, v, qseg, kvseg, *offs, scale, causal, bs,
                           bwd_chunk, bwd_impl, window, softcap, sinks,
                           max_mode)
    if q.ndim == 4:
        b, hq, m, d = q.shape
        kf = k.reshape(b * k.shape[1], *k.shape[2:])
        vf = v.reshape(b * v.shape[1], *v.shape[2:])
        out = _flash_diff(
            q.reshape(b * hq, m, d), kf, vf, None, None, *offs, scale,
            causal, bs, bwd_chunk, bwd_impl, window, softcap, sinks,
            max_mode,
        )
        return out.reshape(b, hq, m, -1)
    raise ValueError(f"unsupported rank {q.ndim}")
