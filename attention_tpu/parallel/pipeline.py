"""GPipe-style pipeline parallelism over a 1D mesh axis.

Not in the reference (a single op has no layer axis; SURVEY §2 marks
pipeline parallelism N/A there) — this is the layer-level scaling leg a
complete framework needs alongside dp/sp/tp/ep.

Schedule, the TPU way: every device holds ONE stage's params (leading
pytree axis sharded over ``pp``); microbatches march through the ring
with ``lax.ppermute`` under a ``lax.scan`` of ticks.  At tick t device
p computes microbatch t-p (the classic GPipe diagonal); fill/drain
bubbles execute on zero inputs (static shapes, no data-dependent
control flow).  The activation hand-off is a data dependency, so XLA's
latency-hiding scheduler overlaps the ppermute with the next tick's
compute — the reference's ping-pong `MPI_Ibcast`/compute overlap
(`attention-mpi.c:268-330`), reborn one axis up.

Backward: plain ``jax.grad`` through the scan+ppermute gives the exact
transposed schedule (ppermute reverses direction under AD) — a correct
1F-then-1B pipeline without hand-written backward passes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.parallel.mesh import default_mesh, shard_map


def pipeline_apply(
    stage_fn,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "pp",
    n_micro: int | None = None,
):
    """Run ``x`` through all pipeline stages; returns the final output.

    ``stage_fn(params_slice, x_mb) -> y_mb`` applies one stage to one
    microbatch (shape-preserving).  ``stage_params`` is a pytree whose
    leaves all have leading axis = number of stages (= mesh size on
    ``axis_name``); slice p lives on device p.  ``x`` (B, ...) is split
    into ``n_micro`` microbatches along axis 0 (default: one per
    stage).  Output is (B, ...), replicated across the axis.
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_stages = mesh.shape[axis_name]
    if n_micro is None:
        n_micro = n_stages
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    leaves = jax.tree_util.tree_leaves(stage_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading axis {leaf.shape[0]} != "
                f"pipeline size {n_stages} on '{axis_name}'"
            )
    mb = b // n_micro
    rest = x.shape[1:]
    xm = x.reshape(n_micro, mb, *rest)
    # no wrap edge: stage 0 reads from the input queue, so the
    # (n_stages-1 -> 0) payload would be discarded — skipping the pair
    # saves one dead activation transfer per tick (devices with no
    # source receive zeros)
    perm = [(j, j + 1) for j in range(n_stages - 1)]

    params_spec = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stage_params
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=(params_spec, P()),
        out_specs=P(),
    )
    def run(params_local, xm_repl):
        p = lax.axis_index(axis_name)
        params_slice = jax.tree_util.tree_map(lambda a: a[0], params_local)
        recv0 = jnp.zeros((mb, *rest), x.dtype)
        out0 = jnp.zeros((n_micro, mb, *rest), x.dtype)

        def tick(carry, t):
            recv, outputs = carry
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            first_in = lax.dynamic_index_in_dim(
                xm_repl, mb_idx, 0, keepdims=False
            )
            inp = jnp.where(p == 0, first_in, recv)
            out = stage_fn(params_slice, inp)
            # each device's carried value next tick = this tick's output
            # of its left neighbor
            send = lax.ppermute(out, axis_name, perm)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            valid = jnp.logical_and(t >= n_stages - 1, p == n_stages - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
            upd = jnp.where(valid, out.astype(outputs.dtype), cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd,
                                                      out_idx, 0)
            return (send, outputs), None

        (_, outputs), _ = lax.scan(
            tick, (recv0, out0), jnp.arange(n_micro + n_stages - 1)
        )
        # only the last stage's buffer is real; masked psum replicates it
        outputs = lax.psum(
            jnp.where(p == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name,
        )
        return outputs.reshape(b, *rest)

    return run(stage_params, xm)
