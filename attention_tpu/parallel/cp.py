"""Differentiable context-parallel flash attention for training.

This is the integration the reference actually is: one orchestrator that
composes the local fused kernel with the distribution scheme
(`attention-mpi.c:191-407` — partitioning, distribution, local online
softmax, global merge in a single `attention()` entry).  Here the
composition must additionally be *differentiable*, because the framework
trains through it: the sharded training step runs the Pallas flash
custom VJP under the mesh rather than leaving sharded-sequence attention
to XLA's auto-SPMD all-gather of the dense einsum path.

Scheme (all-gather context parallelism):

  * activations enter sequence-sharded over the ``cp`` axis (the
    training layout — every other layer of the model is local in the
    sequence dim);
  * inside ``shard_map`` each device all-gathers the (small, GQA) K/V
    heads over the cp axis and runs the fused flash kernel on its local
    Q shard with ``q_offset = axis_index * m_local`` — the kernel's
    dynamic-offset contract keeps causal/window masking globally
    correct (`ops/flash.py::_flash_kernel` offsets_ref);
  * the backward needs no hand-written collective: JAX transposes the
    ``all_gather`` to a ``psum_scatter``, which reduce-scatters each
    device's full-sequence dK/dV contribution back to its shard, and
    the flash custom VJP (`ops/flash_vjp.py`) differentiates the local
    kernel with the same offsets.

Compared to rotating KV around the ring (`parallel/ring.py`), the
all-gather form trades O(n) peak KV memory per device for a single
bulk collective that XLA can schedule ahead of the kernel; for training
blocks where K/V are `(B, H_kv, n, d)` bf16 this is the standard
Megatron/MaxText CP layout.  The ring remains the serving/131k path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.ops.flash import BlockSizes
from attention_tpu.ops.flash_vjp import flash_attention_diff
from attention_tpu.parallel.mesh import shard_map


def _maybe_axis(mesh: Mesh, axis: str | None, dim: int) -> str | None:
    """Use ``axis`` for a dim only if the mesh has it and it divides."""
    if axis is None or axis not in mesh.axis_names:
        return None
    if dim % mesh.shape[axis] != 0:
        return None
    return axis


def cp_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: str | None = "dp",
    head_axis: str | None = "tp",
    scale: float | None = None,
    causal: bool = True,
    window: int | None = None,
    sinks: int | None = None,
    softcap: float | None = None,
    q_segment_ids=None,
    kv_segment_ids=None,
    block_sizes: BlockSizes | None = None,
    bwd_impl: str = "pallas",
    max_mode: str = "bound",
) -> jax.Array:
    """Context-parallel fused attention, differentiable end to end.

    ``q``/``k``/``v`` are (B, H, S, dh) or (H, S, dh) with the sequence
    axis sharded (or shardable) over ``axis_name``; B/H may additionally
    shard over ``batch_axis``/``head_axis`` when present in the mesh and
    divisible (both Q and KV head counts must divide for the head axis
    to be used).  Returns attention output sharded exactly like Q.

    GQA is supported (KV heads dividing Q heads); ``window`` needs
    ``causal=True``; ``sinks`` compose too (the gathered KV holds the
    absolute sink positions, so only q_offset awareness is needed —
    including the backward's sink sliver).  Packed-sequence segment ids
    ((m,)/(n,) global int32; 3D inputs only — the kernel's segment
    limit) shard with Q and replicate with the gathered KV.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh {mesh.axis_names} has no axis {axis_name!r}")
    if q.ndim not in (3, 4):
        raise ValueError(f"cp attention takes 3D/4D inputs, got {q.ndim}D")
    n_dev = mesh.shape[axis_name]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    m = q.shape[-2]
    n = k.shape[-2]
    m_pad = -(-m // n_dev) * n_dev
    n_pad = -(-n // n_dev) * n_dev
    if m_pad != m:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 2) + [(0, m_pad - m), (0, 0)])
    if n_pad != n:
        pad = [(0, 0)] * (k.ndim - 2) + [(0, n_pad - n), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    m_local = m_pad // n_dev

    h_axis = _maybe_axis(mesh, head_axis, q.shape[-3])
    if h_axis is not None and k.shape[-3] % mesh.shape[h_axis] != 0:
        h_axis = None  # KV heads must split too (GQA grouping per shard)
    if q.ndim == 4:
        b_axis = _maybe_axis(mesh, batch_axis, q.shape[0])
        spec = P(b_axis, h_axis, axis_name, None)
    else:
        spec = P(h_axis, axis_name, None)
    seq_axis = q.ndim - 2

    segmented = q_segment_ids is not None
    if segmented != (kv_segment_ids is not None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    if segmented and q.ndim == 4:
        raise ValueError(
            "segment ids support 3D inputs (ids shared across heads)"
        )
    in_specs = [spec, spec, spec]
    extra = []
    if segmented:
        q_seg = jnp.asarray(q_segment_ids, jnp.int32)
        kv_seg = jnp.asarray(kv_segment_ids, jnp.int32)
        if m_pad != m:
            q_seg = jnp.pad(q_seg, (0, m_pad - m), constant_values=-1)
        if n_pad != n:
            kv_seg = jnp.pad(kv_seg, (0, n_pad - n), constant_values=-1)
        # Q ids shard with Q rows; KV ids replicate (the gathered KV is
        # the full sequence on every device)
        extra = [q_seg, kv_seg]
        in_specs += [P(axis_name), P()]

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=tuple(in_specs),
        out_specs=spec,
    )
    def run(q_local, k_local, v_local, *seg_local):
        idx = lax.axis_index(axis_name)
        k_full = lax.all_gather(k_local, axis_name, axis=seq_axis,
                                tiled=True)
        v_full = lax.all_gather(v_local, axis_name, axis=seq_axis,
                                tiled=True)
        return flash_attention_diff(
            q_local, k_full, v_full,
            scale=scale, causal=causal,
            q_offset=idx * m_local,
            kv_valid=n if n_pad != n else None,
            window=window, sinks=sinks, softcap=softcap,
            q_segment_ids=seg_local[0] if seg_local else None,
            kv_segment_ids=seg_local[1] if seg_local else None,
            block_sizes=block_sizes, bwd_impl=bwd_impl,
            max_mode=max_mode,
        )

    out = run(q, k, v, *extra)
    if m_pad != m:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out
