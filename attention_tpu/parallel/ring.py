"""Ring attention: blockwise context parallelism over the ICI ring.

The reference scales long sequences by sharding KV once and all-reducing
softmax stats per Q batch (`attention-mpi.c:340-362`).  Ring attention is
the stronger long-context schedule the reference lacks (SURVEY §2
"parallelism-strategy inventory"): Q *and* KV are sequence-sharded, and KV
shards rotate around the ring with ``lax.ppermute`` while each device
accumulates online-softmax partials for its own Q shard.  After R steps
every device has attended its queries to the full sequence with only
nearest-neighbor ICI traffic and O(n/R) memory per chip — this is what
makes the seq=131072 BASELINE config fit.

The reference's ping-pong discipline lives on in two forms:

  * the per-step online merge of (contrib, lmax, lsum) partials is the same
    rmax/rsum rescale as `attention-mpi.c:179-181`, applied across ring
    steps instead of KV rows;
  * the next KV shard's ``ppermute`` is issued before the current step's
    compute, so XLA's latency-hiding scheduler overlaps transfer with the
    flash kernel — the `MPI_Ibcast`/compute overlap of
    `attention-mpi.c:319-330` expressed as a data dependency instead of
    explicit waits.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.ops.flash import BlockSizes, flash_attention_partials
from attention_tpu.parallel.mesh import default_mesh

NEG_INF = float("-inf")


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "block_sizes", "causal",
                     "softcap"),
)
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    causal: bool = False,
    softcap: float | None = None,
) -> jax.Array:
    """Ring attention over a 1D mesh axis; output is Q-sharded like Q.

    Accepts the same 2D/3D/4D shapes as :func:`flash_attention`.  The
    sequence axes of Q and K/V are sharded over ``axis_name``; both are
    padded to a multiple of the ring size, with padded KV rows masked via
    the kernel's dynamic ``kv_valid`` scalar and padded Q rows sliced off.
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    m = q.shape[-2]
    n = k.shape[-2]
    m_pad = -(-m // n_dev) * n_dev
    n_pad = -(-n // n_dev) * n_dev
    if m_pad != m:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 2) + [(0, m_pad - m), (0, 0)])
    if n_pad != n:
        pad = [(0, 0)] * (k.ndim - 2) + [(0, n_pad - n), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    m_local = m_pad // n_dev
    n_local = n_pad // n_dev

    seq_axis = q.ndim - 2
    seq_spec = P(*([None] * seq_axis), axis_name, None)
    # ring neighbors: shard s moves from device j to device j+1 each step,
    # so after step t device j holds shard (j - t) mod R
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
    )
    def run(q_local, k_local, v_local):
        idx = lax.axis_index(axis_name)
        out_shape = q_local.shape[:-1] + (v_local.shape[-1],)
        acc = jnp.zeros(out_shape, jnp.float32)
        m_run = jnp.full(q_local.shape[:-1], NEG_INF, jnp.float32)
        l_run = jnp.zeros(q_local.shape[:-1], jnp.float32)

        # Unrolled ring schedule (n_dev is static and small): step t computes
        # on the shard currently held and — except on the last step, which
        # needs no further rotation — first issues the ppermute for step
        # t+1 so XLA overlaps the collective with the flash call (no data
        # dependency between them).
        k_cur, v_cur = k_local, v_local
        for t in range(n_dev):
            if t + 1 < n_dev:
                k_next = lax.ppermute(k_cur, axis_name, perm)
                v_next = lax.ppermute(v_cur, axis_name, perm)
            shard = (idx - t) % n_dev  # which global KV shard we hold now
            kv_valid = jnp.clip(n - shard * n_local, 0, n_local)
            out_un, lmax, lsum = flash_attention_partials(
                q_local,
                k_cur,
                v_cur,
                scale=scale,
                block_sizes=block_sizes,
                causal=causal,
                q_offset=idx * m_local,
                kv_offset=shard * n_local,
                kv_valid=kv_valid,
                softcap=softcap,
            )
            # online merge across ring steps (rmax/rsum recurrence,
            # attention-mpi.c:179-181)
            m_new = jnp.maximum(m_run, lmax)
            c_old = jnp.where(m_run == NEG_INF, 0.0, jnp.exp(m_run - m_new))
            c_new = jnp.where(lmax == NEG_INF, 0.0, jnp.exp(lmax - m_new))
            acc = acc * c_old[..., None] + out_un * c_new[..., None]
            l_run = l_run * c_old + lsum * c_new
            m_run = m_new
            if t + 1 < n_dev:
                k_cur, v_cur = k_next, v_next
        l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
        return (acc / l_safe[..., None]).astype(q_local.dtype)

    out = run(q, k, v)
    if m_pad != m:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out
