"""Ring attention: blockwise context parallelism over the ICI ring.

The reference scales long sequences by sharding KV once and all-reducing
softmax stats per Q batch (`attention-mpi.c:340-362`).  Ring attention is
the stronger long-context schedule the reference lacks (SURVEY §2
"parallelism-strategy inventory"): Q *and* KV are sequence-sharded, and KV
shards rotate around the ring with ``lax.ppermute`` while each device
accumulates online-softmax partials for its own Q shard.  After R steps
every device has attended its queries to the full sequence with only
nearest-neighbor ICI traffic and O(n/R) memory per chip — this is what
makes the seq=131072 BASELINE config fit.

The reference's ping-pong discipline lives on in two forms:

  * the per-step online merge of (contrib, lmax, lsum) partials is the same
    rmax/rsum rescale as `attention-mpi.c:179-181`, applied across ring
    steps instead of KV rows;
  * the next KV shard's ``ppermute`` is issued before the current step's
    compute, so XLA's latency-hiding scheduler overlaps transfer with the
    flash kernel — the `MPI_Ibcast`/compute overlap of
    `attention-mpi.c:319-330` expressed as a data dependency instead of
    explicit waits.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.ops.flash import BlockSizes, flash_attention_partials
from attention_tpu.parallel.mesh import default_mesh, shard_map

NEG_INF = float("-inf")


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "block_sizes", "causal",
                     "softcap", "schedule", "window", "sinks", "max_mode"),
)
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    causal: bool = False,
    softcap: float | None = None,
    schedule: str = "contiguous",
    window: int | None = None,
    sinks: int | None = None,
    q_segment_ids=None,
    kv_segment_ids=None,
    max_mode: str = "bound",
) -> jax.Array:
    """Ring attention over a 1D mesh axis; output is Q-sharded like Q.

    Accepts the same 2D/3D/4D shapes as :func:`flash_attention`.  The
    sequence axes of Q and K/V are sharded over ``axis_name``; both are
    padded to a multiple of the ring size, with padded KV rows masked via
    the kernel's dynamic ``kv_valid`` scalar and padded Q rows sliced off.

    ``schedule="zigzag"`` (causal only) interleaves sequence chunks so
    every device carries equal unmasked work at EVERY ring step — the
    load balance the reference had by construction (owner partitioner,
    ±1 row, `attention-mpi.c:19-27`) and the contiguous causal ring
    lacks (early-shard devices spend most steps on fully-masked
    partials).  See :func:`_zigzag_ring`.

    The kernel's masking surface flows through BOTH schedules:
    ``window``/``sinks`` (expressed in GLOBAL positions via each step's
    rotating ``kv_offset`` — sink contributions arrive when the shard
    holding the sequence head rotates in) and packed-sequence segment
    ids (1D global ids; segment matching is equality-based, so the
    zigzag layout change costs nothing — each chunk-pair call just
    slices its chunks' ids from a replicated vector, cheaper than
    rotating a second buffer).
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if schedule not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring schedule {schedule!r}")
    segmented = q_segment_ids is not None
    if segmented != (kv_segment_ids is not None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    if schedule == "zigzag":
        if not causal:
            raise ValueError(
                "zigzag schedule only helps causal attention (non-causal "
                "ring work is already balanced); use schedule='contiguous'"
            )
        return _zigzag_ring(
            q, k, v, mesh=mesh, axis_name=axis_name, scale=scale,
            block_sizes=block_sizes, softcap=softcap, window=window,
            sinks=sinks, max_mode=max_mode,
            segment_ids=(q_segment_ids, kv_segment_ids) if segmented
            else None,
        )

    m = q.shape[-2]
    n = k.shape[-2]
    m_pad = -(-m // n_dev) * n_dev
    n_pad = -(-n // n_dev) * n_dev
    if m_pad != m:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 2) + [(0, m_pad - m), (0, 0)])
    if n_pad != n:
        pad = [(0, 0)] * (k.ndim - 2) + [(0, n_pad - n), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    m_local = m_pad // n_dev
    n_local = n_pad // n_dev

    seq_axis = q.ndim - 2
    seq_spec = P(*([None] * seq_axis), axis_name, None)
    # ring neighbors: shard s moves from device j to device j+1 each step,
    # so after step t device j holds shard (j - t) mod R
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    in_specs = [seq_spec, seq_spec, seq_spec]
    extra = []
    if segmented:
        # Q ids sharded with Q; KV ids replicated — each step slices the
        # arriving shard's ids instead of rotating a second buffer
        extra = list(_ring_pad_ids(q_segment_ids, kv_segment_ids,
                                   m, n, m_pad, n_pad))
        in_specs += [P(axis_name), P()]

    run_cfg = _RingCfg(
        axis_name=axis_name, n_dev=n_dev, n=n, m_local=m_local,
        n_local=n_local, scale=scale, block_sizes=block_sizes,
        causal=causal, softcap=softcap, window=window, sinks=sinks,
        max_mode=max_mode,
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=tuple(in_specs),
        out_specs=seq_spec,
    )
    def run(q_local, k_local, v_local, *seg_local):
        # one shared copy of the rotate/merge schedule (also the
        # custom-VJP forward): see _ring_fwd_loop
        out, _ = _ring_fwd_loop(
            q_local, k_local, v_local, run_cfg,
            seg=tuple(seg_local) if seg_local else None,
        )
        return out

    out = run(q, k, v, *extra)
    if m_pad != m:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "batch_axis", "head_axis",
                     "scale", "block_sizes", "causal", "softcap", "window",
                     "sinks", "schedule", "max_mode"),
)
def ring_attention_diff(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    batch_axis: str | None = "dp",
    head_axis: str | None = "tp",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    causal: bool = False,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    schedule: str = "contiguous",
    q_segment_ids=None,
    kv_segment_ids=None,
    max_mode: str = "bound",
) -> jax.Array:
    """Differentiable ring attention: O(n/R) KV memory per device in
    BOTH passes.

    The all-gather CP path (`parallel/cp.py`) is the default training
    composition but holds the full K/V per device; this is the
    long-context alternative where even K/V exceed one device.  The
    forward is the contiguous ring (online merge of rotating-shard
    partials, saving the per-row lse); the custom backward runs a
    second ring in which dK/dV accumulators TRAVEL WITH their shard —
    each step calls the offset-aware Pallas backward kernels
    (`flash_backward(q_offset=, kv_offset=, kv_valid=)`) on the local Q
    block against the visiting shard, and a final rotation delivers
    each shard's gradients home.  Ring traffic doubles in the backward
    (k, v, dk, dv rotate together) — the standard ring-attention
    gradient schedule.

    Shapes: (h, m, d) or (b, h, m, d), GQA supported; sequence axes
    sharded over ``axis_name``.  ``window`` requires ``causal``.
    Packed-sequence segment ids ((m,)/(n,) global int32 vectors; 3D
    inputs only — the kernel's ids-shared-across-heads limit) flow
    through BOTH passes of BOTH schedules: Q ids shard with Q on the
    contiguous ring and ride replicated on the zigzag (whose chunk
    calls slice by chunk id — segment matching is positionless), KV
    ids stay replicated and are sliced per visiting shard.

    ``sinks`` (StreamingLLM, requires ``window``) train under the ring
    too: the forward's banded partials handle the sink blocks through
    each step's ``kv_offset``; the backward adds the out-of-window sink
    sliver (`flash_bwd._sink_patch`) exactly once — gated to the ring
    step where the shard holding the absolute sink rows (shard 0, or
    zigzag chunk 0) is resident, so its dK/dV land in that shard's
    traveling gradient buffer.  Sinks must fit in one shard/chunk.

    ``schedule="zigzag"`` (causal self-attention only) applies the
    per-step load balance to BOTH passes: each device differentiates
    its early+late chunk pair, so forward partials and the backward's
    three chunk-pair kernel calls carry equal work on every device at
    every step — the training-time answer to the contiguous causal
    ring's R-fold per-step skew.
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if q.ndim not in (3, 4):
        raise ValueError(f"ring_attention_diff takes 3D/4D, got {q.ndim}D")
    if schedule not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring schedule {schedule!r}")
    segmented = q_segment_ids is not None
    if segmented != (kv_segment_ids is not None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    if segmented and q.ndim == 4:
        raise ValueError(
            "segment ids support 3D inputs (ids shared across heads)"
        )
    if sinks is not None:
        if window is None:
            raise ValueError("sinks require window= (see flash_attention)")
        if segmented:
            raise ValueError("sinks do not compose with segment_ids")
    if schedule == "zigzag":
        if not causal:
            raise ValueError("zigzag schedule requires causal=True")
        return _zigzag_ring_diff(
            q, k, v, mesh=mesh, axis_name=axis_name,
            batch_axis=batch_axis, head_axis=head_axis, scale=scale,
            block_sizes=block_sizes, softcap=softcap, window=window,
            sinks=sinks, max_mode=max_mode,
            segment_ids=(q_segment_ids, kv_segment_ids) if segmented
            else None,
        )

    m = q.shape[-2]
    n = k.shape[-2]
    m_pad = -(-m // n_dev) * n_dev
    n_pad = -(-n // n_dev) * n_dev
    if m_pad != m:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 2) + [(0, m_pad - m), (0, 0)])
    if n_pad != n:
        pad = [(0, 0)] * (k.ndim - 2) + [(0, n_pad - n), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    m_local = m_pad // n_dev
    n_local = n_pad // n_dev
    seq_axis = q.ndim - 2
    # batch/head axes shard over the rest of the training mesh when
    # present and divisible (both Q and KV head counts for the head
    # axis), mirroring parallel/cp.py — the ring itself runs over
    # ``axis_name`` only
    from attention_tpu.parallel.cp import _maybe_axis

    h_axis = _maybe_axis(mesh, head_axis, q.shape[-3])
    if h_axis is not None and k.shape[-3] % mesh.shape[h_axis] != 0:
        h_axis = None
    if q.ndim == 4:
        b_axis = _maybe_axis(mesh, batch_axis, q.shape[0])
        seq_spec = P(b_axis, h_axis, axis_name, None)
    else:
        seq_spec = P(h_axis, axis_name, None)

    if sinks is not None and sinks > n_local:
        raise ValueError(
            f"sinks ({sinks}) must fit in one KV shard ({n_local} rows)"
        )
    cfg = dict(
        axis_name=axis_name, n_dev=n_dev, n=n, m_local=m_local,
        n_local=n_local, scale=scale, block_sizes=block_sizes,
        causal=causal, softcap=softcap, window=window, sinks=sinks,
        max_mode=max_mode,
    )

    in_specs = [seq_spec, seq_spec, seq_spec]
    extra = []
    if segmented:
        # Q ids shard with Q rows; KV ids replicate (sliced per shard)
        extra = list(_ring_pad_ids(q_segment_ids, kv_segment_ids,
                                   m, n, m_pad, n_pad))
        in_specs += [P(axis_name), P()]

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=tuple(in_specs),
        out_specs=seq_spec,
    )
    def run(q_local, k_local, v_local, *seg_local):
        if q_local.ndim == 4:
            # fold batch into heads (grouping per batch stays aligned:
            # hh // group lands on that batch's kv head); segments are
            # 3D-only, so this arm never carries them
            b, h, mm, d = q_local.shape
            bk, hkv, nn, dk_ = k_local.shape
            out = _ring_diff(
                q_local.reshape(b * h, mm, d),
                k_local.reshape(bk * hkv, nn, dk_),
                v_local.reshape(bk * hkv, nn, v_local.shape[-1]),
                _RingCfg(**cfg),
            )
            return out.reshape(b, h, mm, -1)
        return _ring_diff(q_local, k_local, v_local, _RingCfg(**cfg),
                          *seg_local)

    out = run(q, k, v, *extra)
    if m_pad != m:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out


class _RingCfg(NamedTuple):
    axis_name: str
    n_dev: int
    n: int
    m_local: int
    n_local: int
    scale: float
    block_sizes: "BlockSizes | None"
    causal: bool
    softcap: "float | None"
    window: "int | None"
    sinks: "int | None" = None
    max_mode: str = "bound"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ring_diff(q, k, v, cfg: _RingCfg, q_ids=None, kv_ids=None):
    out, _ = _ring_diff_fwd_impl(q, k, v, cfg, q_ids, kv_ids)
    return out


def _ring_fwd_loop(q, k, v, cfg: _RingCfg, seg=None):
    """Contiguous ring forward on LOCAL blocks — THE one copy of the
    rotate/merge schedule, shared by `ring_attention` (which discards
    the lse) and the custom-VJP path (which saves it).  ``seg`` is an
    optional (q_ids_local, kv_ids_full) pair; each step slices the
    arriving shard's KV ids from the replicated vector.  Returns
    (normalized out, natural-log lse)."""
    idx = lax.axis_index(cfg.axis_name)
    perm = [(j, (j + 1) % cfg.n_dev) for j in range(cfg.n_dev)]
    acc = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m_run = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l_run = jnp.zeros(q.shape[:-1], jnp.float32)
    k_cur, v_cur = k, v
    for t in range(cfg.n_dev):
        # prefetch-then-rotate: the next shard's ppermute is issued
        # before this step's compute so XLA overlaps them
        if t + 1 < cfg.n_dev:
            k_next = lax.ppermute(k_cur, cfg.axis_name, perm)
            v_next = lax.ppermute(v_cur, cfg.axis_name, perm)
        shard = (idx - t) % cfg.n_dev
        seg_kw = {}
        if seg is not None:
            seg_kw = {
                "q_segment_ids": seg[0],
                "kv_segment_ids": lax.dynamic_slice(
                    seg[1], (shard * cfg.n_local,), (cfg.n_local,)
                ),
            }
        out_un, lmax, lsum = flash_attention_partials(
            q, k_cur, v_cur, scale=cfg.scale, block_sizes=cfg.block_sizes,
            causal=cfg.causal, q_offset=idx * cfg.m_local,
            kv_offset=shard * cfg.n_local,
            kv_valid=jnp.clip(cfg.n - shard * cfg.n_local, 0, cfg.n_local),
            softcap=cfg.softcap, window=cfg.window, sinks=cfg.sinks,
            max_mode=cfg.max_mode,
            **seg_kw,
        )
        acc, m_run, l_run = _merge_step((acc, m_run, l_run),
                                        out_un, lmax, lsum)
        if t + 1 < cfg.n_dev:
            k_cur, v_cur = k_next, v_next
    l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    lse = jnp.where(l_run == 0.0, NEG_INF, m_run + jnp.log(l_safe))
    return out, lse


def _ring_diff_fwd_impl(q, k, v, cfg: _RingCfg, q_ids=None, kv_ids=None):
    seg = None if q_ids is None else (q_ids, kv_ids)
    out, lse = _ring_fwd_loop(q, k, v, cfg, seg=seg)
    return out, (q, k, v, q_ids, kv_ids, out, lse)


def _ring_diff_fwd(q, k, v, cfg: _RingCfg, q_ids=None, kv_ids=None):
    out, res = _ring_diff_fwd_impl(q, k, v, cfg, q_ids, kv_ids)
    return out, res


def _ring_diff_bwd(cfg: _RingCfg, res, dout):
    from attention_tpu.ops.flash import _should_interpret
    from attention_tpu.ops.flash_bwd import flash_backward
    from attention_tpu.ops.flash_vjp import _seg_zeros

    q, k, v, q_ids, kv_ids, out, lse = res
    idx = lax.axis_index(cfg.axis_name)
    perm = [(j, (j + 1) % cfg.n_dev) for j in range(cfg.n_dev)]
    interpret = _should_interpret()
    dq = jnp.zeros(q.shape, jnp.float32)
    dk_cur = jnp.zeros(k.shape, jnp.float32)
    dv_cur = jnp.zeros(v.shape, jnp.float32)
    k_cur, v_cur = k, v
    dk_s = dv_s = None
    if cfg.sinks is not None:
        # Out-of-window sink pairs: the banded kernel covers only the
        # window band, so the sliver supplies the rest.  The sink rows
        # are shard 0's first `sinks` KV rows — fetch JUST that sliver
        # once (all_gather of O(sinks·d), then shard 0's copy) and
        # compute the patch ONCE per device instead of per ring step
        # (it used to run every step and be where-gated off on all but
        # one — O(n_dev · m · sinks · d) redundant work).
        # kv_valid=None: shard 0 is always fully real (sequence padding
        # lives in the LAST shard) and sinks <= n_local is enforced at
        # entry, so the sink columns can't be padded.
        from attention_tpu.ops.flash_bwd import _sink_patch

        se0 = min(cfg.sinks, k.shape[-2])
        k_sink = lax.all_gather(k[:, :se0], cfg.axis_name)[0]
        v_sink = lax.all_gather(v[:, :se0], cfg.axis_name)[0]
        dq_s, dk_s, dv_s, se = _sink_patch(
            q, k_sink, v_sink, out, lse, dout, scale=cfg.scale,
            window=cfg.window, sinks=cfg.sinks, softcap=cfg.softcap,
            q_offset=idx * cfg.m_local,
        )
        dq = dq + dq_s
    for t in range(cfg.n_dev):
        if t + 1 < cfg.n_dev:
            k_next = lax.ppermute(k_cur, cfg.axis_name, perm)
            v_next = lax.ppermute(v_cur, cfg.axis_name, perm)
        shard = (idx - t) % cfg.n_dev
        seg_kw = {}
        if q_ids is not None:
            seg_kw = {
                "q_segment_ids": q_ids,
                "kv_segment_ids": lax.dynamic_slice(
                    kv_ids, (shard * cfg.n_local,), (cfg.n_local,)
                ),
            }
        dq_i, dk_i, dv_i = flash_backward(
            q, k_cur, v_cur, out, lse, dout,
            scale=cfg.scale, causal=cfg.causal,
            block_sizes=None,  # backward keeps its own tuned defaults
            interpret=interpret, window=cfg.window, softcap=cfg.softcap,
            q_offset=idx * cfg.m_local,
            kv_offset=shard * cfg.n_local,
            kv_valid=jnp.clip(cfg.n - shard * cfg.n_local, 0, cfg.n_local),
            **seg_kw,
        )
        dq = dq + dq_i.astype(jnp.float32)
        # accumulate into the buffer of the shard CURRENTLY resident,
        # THEN rotate it together with the shard (add-before-rotate:
        # the arriving buffer belongs to the NEXT shard)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        if cfg.sinks is not None:
            # the precomputed sink dK/dV must land in shard 0's
            # traveling buffer — gate the (tiny) add to the step where
            # shard 0 is resident; the sliver itself was computed once
            # before the loop against the true sink rows
            gate = shard == 0
            dk_cur = dk_cur.at[:, :se].add(jnp.where(gate, dk_s, 0.0))
            dv_cur = dv_cur.at[:, :se].add(jnp.where(gate, dv_s, 0.0))
        if t + 1 < cfg.n_dev:
            dk_cur = lax.ppermute(dk_cur, cfg.axis_name, perm)
            dv_cur = lax.ppermute(dv_cur, cfg.axis_name, perm)
            k_cur, v_cur = k_next, v_next
    # after R-1 rotations shard s sits at device (s-1) mod R; one more
    # rotation delivers each shard's accumulated gradients home
    dk_home = lax.ppermute(dk_cur, cfg.axis_name, perm)
    dv_home = lax.ppermute(dv_cur, cfg.axis_name, perm)
    return (dq.astype(q.dtype), dk_home.astype(k.dtype),
            dv_home.astype(v.dtype), _seg_zeros(q_ids), _seg_zeros(kv_ids))


_ring_diff.defvjp(_ring_diff_fwd, _ring_diff_bwd)


def _merge_step(state, out_un, lmax, lsum):
    """Online merge of one partials call into a running (acc, m, l)
    state — the rmax/rsum recurrence (`attention-mpi.c:179-181`) applied
    across ring steps; fully-masked calls arrive as lmax=-inf no-ops."""
    acc, m_run, l_run = state
    m_new = jnp.maximum(m_run, lmax)
    c_old = jnp.where(m_run == NEG_INF, 0.0, jnp.exp(m_run - m_new))
    c_new = jnp.where(lmax == NEG_INF, 0.0, jnp.exp(lmax - m_new))
    return (
        acc * c_old[..., None] + out_un * c_new[..., None],
        m_new,
        l_run * c_old + lsum * c_new,
    )


def _zig_prepare(q, k, v, n_dev):
    """Shared zigzag preamble: self-attention shape check + pad the
    sequence to a 2R-chunk multiple.  Returns (q, k, v, chunk, n, m,
    c_pad, seq_axis)."""
    m = q.shape[-2]
    n = k.shape[-2]
    if m != n:
        raise ValueError(
            f"zigzag ring is self-attention-shaped (m == n), got {m} != {n}"
        )
    seq_axis = q.ndim - 2
    n_chunks = 2 * n_dev
    c_pad = -(-n // n_chunks) * n_chunks
    if c_pad != n:
        pad = [(0, 0)] * (q.ndim - 2) + [(0, c_pad - n), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    return q, k, v, c_pad // n_chunks, n, m, c_pad, seq_axis


def _ring_pad_ids(q_segment_ids, kv_segment_ids, m, n, m_pad, n_pad):
    """Validate a (q_ids, kv_ids) pair and pad to the ring-padded
    lengths with DISTINCT negative sentinels (-1 for Q, -2 for KV):
    padded rows match no non-negative real id, and the distinct values
    keep padded Q rows from matching padded KV rows either — the
    output slice-off makes that unobservable today, but the invariant
    no longer depends on it.  Length mismatches must fail at trace
    time: ``lax.dynamic_slice`` CLAMPS out-of-bounds starts, so a
    wrong-length id vector would otherwise hand shards silently wrong
    ids."""
    q_seg = jnp.asarray(q_segment_ids, jnp.int32)
    kv_seg = jnp.asarray(kv_segment_ids, jnp.int32)
    if q_seg.ndim != 1 or kv_seg.ndim != 1:
        raise ValueError("ring segment ids are 1D global vectors")
    if q_seg.shape[0] != m or kv_seg.shape[0] != n:
        raise ValueError(
            f"segment id lengths ({q_seg.shape[0]}, {kv_seg.shape[0]}) "
            f"must match the sequence lengths ({m}, {n})"
        )
    if m_pad != m:
        q_seg = jnp.pad(q_seg, (0, m_pad - m), constant_values=-1)
    if n_pad != n:
        kv_seg = jnp.pad(kv_seg, (0, n_pad - n), constant_values=-2)
    return q_seg, kv_seg


def _zig_pad_ids(segment_ids, m, n, c_pad):
    """Zigzag variant of :func:`_ring_pad_ids`: both vectors pad to the
    2R-chunk-padded length.  Ids stay in GLOBAL order — segment matching
    is equality-based, so the zigzag layout never permutes them; chunk
    calls slice by chunk id instead."""
    return _ring_pad_ids(segment_ids[0], segment_ids[1], m, n,
                         c_pad, c_pad)


def _zigzag_ring(q, k, v, *, mesh, axis_name, scale, block_sizes, softcap,
                 window=None, sinks=None, segment_ids=None,
                 max_mode="bound"):
    """Causal ring attention with the llama-3-style zigzag layout.

    The sequence is split into 2R chunks; device d owns chunks
    (d, 2R-1-d) — one early, one late.  Per ring step each device then
    carries EXACTLY 2·C² causal score work (C = chunk rows): the early
    chunk's missing future work is exactly compensated by the late
    chunk's surplus past work, for every (device, step) pair — the
    per-step analog of the reference's ±1-row owner balance
    (`attention-mpi.c:19-27`).  The contiguous schedule instead gives
    device d at step t either a full, empty, or diagonal shard: device
    R-1 does ~R times the per-step work of device 0, and every step's
    merge waits on the slowest device.

    Of the four (q chunk x kv chunk) pairs per step, (q_lo, kv_hi) is
    empty BY CONSTRUCTION (kv chunk 2R-1-e is always in q chunk d's
    future) and is skipped at trace time; the kernel's dynamic causal
    guard skips the tiles of whichever of (q_lo, kv_lo)/(q_hi, kv_hi)
    is empty at this step.
    """
    n_dev = mesh.shape[axis_name]
    q, k, v, chunk, n, m, c_pad, seq_axis = _zig_prepare(q, k, v, n_dev)
    n_chunks = 2 * n_dev

    # zigzag permutation: device d's contiguous 2-chunk slice holds
    # global chunks (d, 2R-1-d); built as a static numpy gather index
    import numpy as np

    order = []
    for d in range(n_dev):
        order += [d, n_chunks - 1 - d]
    idx = np.concatenate(
        [np.arange(c * chunk, (c + 1) * chunk) for c in order]
    )
    inv = np.empty_like(idx)
    inv[idx] = np.arange(idx.size)
    idx_j = jnp.asarray(idx)
    q_z = jnp.take(q, idx_j, axis=seq_axis)
    k_z = jnp.take(k, idx_j, axis=seq_axis)
    v_z = jnp.take(v, idx_j, axis=seq_axis)

    seq_spec = P(*([None] * seq_axis), axis_name, None)

    zcfg = _ZigCfg(
        axis_name=axis_name, n_dev=n_dev, n=n, chunk=chunk, scale=scale,
        block_sizes=block_sizes, softcap=softcap, window=window,
        sinks=sinks, max_mode=max_mode,
    )

    extra = []
    in_specs = [seq_spec, seq_spec, seq_spec]
    if segment_ids is not None:
        # both id vectors replicated in GLOBAL order; chunk calls slice
        extra = list(_zig_pad_ids(segment_ids, m, n, c_pad))
        in_specs += [P(), P()]

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=tuple(in_specs),
        out_specs=seq_spec,
    )
    def run(q_local, k_local, v_local, *seg_local):
        out_lo, _, out_hi, _ = _zig_fwd_loop(
            q_local, k_local, v_local, zcfg,
            seg=tuple(seg_local) if seg_local else None,
        )
        return jnp.concatenate([out_lo, out_hi], axis=seq_axis)

    out = run(q_z, k_z, v_z, *extra)
    out = jnp.take(out, jnp.asarray(inv), axis=seq_axis)
    if c_pad != n:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out


class _ZigCfg(NamedTuple):
    axis_name: str
    n_dev: int
    n: int
    chunk: int
    scale: float
    block_sizes: "BlockSizes | None"
    softcap: "float | None"
    window: "int | None"
    sinks: "int | None" = None
    max_mode: str = "bound"


def _zig_slices(ndim, chunk):
    sl_lo = tuple([slice(None)] * (ndim - 2) + [slice(0, chunk)])
    sl_hi = tuple([slice(None)] * (ndim - 2) + [slice(chunk, None)])
    return sl_lo, sl_hi


def _zig_chunk_ids(ids_full, cid, chunk):
    """Slice chunk ``cid``'s ids from a replicated global id vector
    (``cid`` is a traced device-dependent chunk index)."""
    return lax.dynamic_slice(ids_full, (cid * chunk,), (chunk,))


def _zig_fwd_loop(q_local, k_local, v_local, z: _ZigCfg, seg=None):
    """The one copy of the zigzag rotate/merge schedule, shared by the
    plain forward (which discards the lse) and the custom-VJP path.
    ``seg`` is an optional (q_ids_full, kv_ids_full) pair of replicated
    GLOBAL id vectors; every chunk-pair call slices its chunks' ids
    (segment matching is positionless, so the zigzag layout needs no id
    permutation).  Returns (out_lo, lse_lo, out_hi, lse_hi) for the
    device's two chunks."""
    n_chunks = 2 * z.n_dev
    idx_d = lax.axis_index(z.axis_name)
    a = idx_d  # early chunk id
    b = n_chunks - 1 - idx_d  # late chunk id
    perm = [(j, (j + 1) % z.n_dev) for j in range(z.n_dev)]
    sl_lo, sl_hi = _zig_slices(q_local.ndim, z.chunk)
    q_lo, q_hi = q_local[sl_lo], q_local[sl_hi]
    if seg is not None:
        q_seg_lo = _zig_chunk_ids(seg[0], a, z.chunk)
        q_seg_hi = _zig_chunk_ids(seg[0], b, z.chunk)

    def fresh(q_c):
        shape = q_c.shape[:-1]
        return (
            jnp.zeros(shape + (v_local.shape[-1],), jnp.float32),
            jnp.full(shape, NEG_INF, jnp.float32),
            jnp.zeros(shape, jnp.float32),
        )

    lo = fresh(q_lo)
    hi = fresh(q_hi)

    def partial_call(q_c, k_c, v_c, q_cid, kv_cid, q_seg_c=None):
        seg_kw = {}
        if seg is not None:
            seg_kw = {
                "q_segment_ids": q_seg_c,
                "kv_segment_ids": _zig_chunk_ids(seg[1], kv_cid, z.chunk),
            }
        return flash_attention_partials(
            q_c, k_c, v_c, scale=z.scale, block_sizes=z.block_sizes,
            causal=True,
            q_offset=q_cid * z.chunk,
            kv_offset=kv_cid * z.chunk,
            kv_valid=jnp.clip(z.n - kv_cid * z.chunk, 0, z.chunk),
            softcap=z.softcap,
            window=z.window,
            sinks=z.sinks,
            max_mode=z.max_mode,
            **seg_kw,
        )

    seg_lo = None if seg is None else q_seg_lo
    seg_hi = None if seg is None else q_seg_hi
    k_cur, v_cur = k_local, v_local
    for t in range(z.n_dev):
        if t + 1 < z.n_dev:
            k_next = lax.ppermute(k_cur, z.axis_name, perm)
            v_next = lax.ppermute(v_cur, z.axis_name, perm)
        e = (idx_d - t) % z.n_dev  # whose KV pair we hold now
        ae = e
        be = n_chunks - 1 - e
        k_lo, k_hi = k_cur[sl_lo], k_cur[sl_hi]
        v_lo, v_hi = v_cur[sl_lo], v_cur[sl_hi]
        # (q_hi, kv_lo): always fully unmasked (b > ae)
        hi = _merge_step(hi, *partial_call(q_hi, k_lo, v_lo, b, ae, seg_hi))
        # (q_lo, kv_lo): nonempty iff ae <= a — dynamic kernel skip
        lo = _merge_step(lo, *partial_call(q_lo, k_lo, v_lo, a, ae, seg_lo))
        # (q_hi, kv_hi): nonempty iff be <= b — dynamic kernel skip
        hi = _merge_step(hi, *partial_call(q_hi, k_hi, v_hi, b, be, seg_hi))
        # (q_lo, kv_hi): empty by construction — skipped at trace time
        if t + 1 < z.n_dev:
            k_cur, v_cur = k_next, v_next

    def finalize(state, q_c):
        acc, m_run, l_run = state
        l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
        out = (acc / l_safe[..., None]).astype(q_c.dtype)
        lse = jnp.where(l_run == 0.0, NEG_INF, m_run + jnp.log(l_safe))
        return out, lse

    out_lo, lse_lo = finalize(lo, q_lo)
    out_hi, lse_hi = finalize(hi, q_hi)
    return out_lo, lse_lo, out_hi, lse_hi


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _zig_diff(q, k, v, z: _ZigCfg, q_ids=None, kv_ids=None):
    seg = None if q_ids is None else (q_ids, kv_ids)
    out_lo, _, out_hi, _ = _zig_fwd_loop(q, k, v, z, seg=seg)
    return jnp.concatenate([out_lo, out_hi], axis=-2)


def _zig_diff_fwd(q, k, v, z: _ZigCfg, q_ids=None, kv_ids=None):
    seg = None if q_ids is None else (q_ids, kv_ids)
    out_lo, lse_lo, out_hi, lse_hi = _zig_fwd_loop(q, k, v, z, seg=seg)
    out = jnp.concatenate([out_lo, out_hi], axis=-2)
    return out, (q, k, v, q_ids, kv_ids, out_lo, lse_lo, out_hi, lse_hi)


def _zig_diff_bwd(z: _ZigCfg, res, dout):
    """Backward zigzag ring: the kv-pair gradient buffers travel with
    their pair (add-before-rotate; one final rotation delivers home),
    and every (device, step) carries the same 3-call balanced work as
    the forward — the load-balance property holds in BOTH passes."""
    from attention_tpu.ops.flash import _should_interpret
    from attention_tpu.ops.flash_bwd import flash_backward
    from attention_tpu.ops.flash_vjp import _seg_zeros

    q, k, v, q_ids, kv_ids, out_lo, lse_lo, out_hi, lse_hi = res
    n_chunks = 2 * z.n_dev
    idx_d = lax.axis_index(z.axis_name)
    a = idx_d
    b = n_chunks - 1 - idx_d
    perm = [(j, (j + 1) % z.n_dev) for j in range(z.n_dev)]
    interpret = _should_interpret()
    sl_lo, sl_hi = _zig_slices(q.ndim, z.chunk)
    q_lo, q_hi = q[sl_lo], q[sl_hi]
    dout_lo, dout_hi = dout[sl_lo], dout[sl_hi]
    seg_lo = seg_hi = None
    if q_ids is not None:
        seg_lo = _zig_chunk_ids(q_ids, a, z.chunk)
        seg_hi = _zig_chunk_ids(q_ids, b, z.chunk)
    dq_lo = jnp.zeros(q_lo.shape, jnp.float32)
    dq_hi = jnp.zeros(q_hi.shape, jnp.float32)
    dk_cur = jnp.zeros(k.shape, jnp.float32)
    dv_cur = jnp.zeros(v.shape, jnp.float32)
    k_cur, v_cur = k, v
    s12k = s12v = None
    if z.sinks is not None:
        # out-of-window sink pairs (see the contiguous backward): the
        # absolute sink rows live in global chunk 0 = device 0's early
        # chunk; fetch just that sliver once and compute both local q
        # chunks' patches ONCE instead of twice per ring step.
        # kv_valid=None: chunk 0 is always fully real (sequence padding
        # lives in the LAST chunks) and sinks <= chunk is enforced at
        # entry, so the sink columns can't be padded.
        from attention_tpu.ops.flash_bwd import _sink_patch

        se0 = min(z.sinks, z.chunk)
        k_sink = lax.all_gather(k[sl_lo][:, :se0], z.axis_name)[0]
        v_sink = lax.all_gather(v[sl_lo][:, :se0], z.axis_name)[0]
        s1q, s1k, s1v, se = _sink_patch(
            q_hi, k_sink, v_sink, out_hi, lse_hi, dout_hi,
            scale=z.scale, window=z.window, sinks=z.sinks,
            softcap=z.softcap, q_offset=b * z.chunk)
        s2q, s2k, s2v, _ = _sink_patch(
            q_lo, k_sink, v_sink, out_lo, lse_lo, dout_lo,
            scale=z.scale, window=z.window, sinks=z.sinks,
            softcap=z.softcap, q_offset=a * z.chunk)
        dq_hi = dq_hi + s1q
        dq_lo = dq_lo + s2q
        s12k = s1k + s2k
        s12v = s1v + s2v

    def bwd_call(q_c, k_c, v_c, out_c, lse_c, dout_c, q_cid, kv_cid,
                 q_seg_c=None):
        seg_kw = {}
        if q_ids is not None:
            seg_kw = {
                "q_segment_ids": q_seg_c,
                "kv_segment_ids": _zig_chunk_ids(kv_ids, kv_cid, z.chunk),
            }
        return flash_backward(
            q_c, k_c, v_c, out_c, lse_c, dout_c,
            scale=z.scale, causal=True, interpret=interpret,
            window=z.window, softcap=z.softcap,
            q_offset=q_cid * z.chunk,
            kv_offset=kv_cid * z.chunk,
            kv_valid=jnp.clip(z.n - kv_cid * z.chunk, 0, z.chunk),
            **seg_kw,
        )

    for t in range(z.n_dev):
        if t + 1 < z.n_dev:
            k_next = lax.ppermute(k_cur, z.axis_name, perm)
            v_next = lax.ppermute(v_cur, z.axis_name, perm)
        e = (idx_d - t) % z.n_dev
        ae = e
        be = n_chunks - 1 - e
        k_lo, k_hi = k_cur[sl_lo], k_cur[sl_hi]
        v_lo, v_hi = v_cur[sl_lo], v_cur[sl_hi]
        # the forward's three chunk-pair calls, differentiated
        g1q, g1k, g1v = bwd_call(q_hi, k_lo, v_lo, out_hi, lse_hi,
                                 dout_hi, b, ae, seg_hi)
        g2q, g2k, g2v = bwd_call(q_lo, k_lo, v_lo, out_lo, lse_lo,
                                 dout_lo, a, ae, seg_lo)
        g3q, g3k, g3v = bwd_call(q_hi, k_hi, v_hi, out_hi, lse_hi,
                                 dout_hi, b, be, seg_hi)
        dq_hi = dq_hi + g1q.astype(jnp.float32) + g3q.astype(jnp.float32)
        dq_lo = dq_lo + g2q.astype(jnp.float32)
        # upcast each term BEFORE adding (with bf16 k/v the kernel
        # returns bf16 grads; a bf16+bf16 add would round pre-buffer)
        dk_cur = dk_cur.at[sl_lo].add(
            g1k.astype(jnp.float32) + g2k.astype(jnp.float32))
        dk_cur = dk_cur.at[sl_hi].add(g3k.astype(jnp.float32))
        dv_cur = dv_cur.at[sl_lo].add(
            g1v.astype(jnp.float32) + g2v.astype(jnp.float32))
        dv_cur = dv_cur.at[sl_hi].add(g3v.astype(jnp.float32))
        if z.sinks is not None:
            # the precomputed sink dK/dV land in global chunk 0's
            # traveling buffer — resident as the visiting EARLY chunk
            # when ae == 0; the slivers themselves were computed once
            # before the loop against the true sink rows
            gate = ae == 0
            dk_cur = dk_cur.at[:, :se].add(jnp.where(gate, s12k, 0.0))
            dv_cur = dv_cur.at[:, :se].add(jnp.where(gate, s12v, 0.0))
        if t + 1 < z.n_dev:
            dk_cur = lax.ppermute(dk_cur, z.axis_name, perm)
            dv_cur = lax.ppermute(dv_cur, z.axis_name, perm)
            k_cur, v_cur = k_next, v_next
    dk_home = lax.ppermute(dk_cur, z.axis_name, perm)
    dv_home = lax.ppermute(dv_cur, z.axis_name, perm)
    dq = jnp.concatenate([dq_lo, dq_hi], axis=-2)
    return (dq.astype(q.dtype), dk_home.astype(k.dtype),
            dv_home.astype(v.dtype), _seg_zeros(q_ids), _seg_zeros(kv_ids))


_zig_diff.defvjp(_zig_diff_fwd, _zig_diff_bwd)


def _zigzag_exchange(x, axis_name, n_dev, chunk, *, inverse=False):
    """Reshard between contiguous 2-chunk slices and zigzag (early,
    late) slices WITHOUT a global gather — two half-chunk ppermutes
    plus per-device slot selects, all inside shard_map, so the layout
    change stays SPMD-partitionable however the caller's jit shards
    the inputs (a plain `jnp.take` permutation over an sp-sharded
    sequence fails XLA's partitioner).

    Forward: contiguous device d holds chunks (2d, 2d+1); zigzag device
    r wants (r, 2R-1-r).  Since 2R-1 is odd, each device's two target
    chunks always have opposite parity, so the even-chunk and odd-chunk
    flows each form a bijective device permutation.
    """
    n_chunks = 2 * n_dev
    sl_lo, sl_hi = _zig_slices(x.ndim, chunk)
    seq_axis = x.ndim - 2
    r = lax.axis_index(axis_name)
    even = (r % 2) == 0

    def dest_of_chunk(c):
        return c if c < n_dev else n_chunks - 1 - c

    if not inverse:
        h0, h1 = x[sl_lo], x[sl_hi]  # chunks 2d, 2d+1
        perm0 = [(d, dest_of_chunk(2 * d)) for d in range(n_dev)]
        perm1 = [(d, dest_of_chunk(2 * d + 1)) for d in range(n_dev)]
        arr0 = lax.ppermute(h0, axis_name, perm0)  # the even chunk
        arr1 = lax.ppermute(h1, axis_name, perm1)  # the odd chunk
        # device r's early chunk is r (parity r%2), late is 2R-1-r
        lo = jnp.where(even, arr0, arr1)
        hi = jnp.where(even, arr1, arr0)
        return jnp.concatenate([lo, hi], axis=seq_axis)
    # inverse: zigzag device r holds (lo=chunk r, hi=chunk 2R-1-r);
    # route the even/odd chunks back to contiguous device c//2
    lo, hi = x[sl_lo], x[sl_hi]
    a = jnp.where(even, lo, hi)  # the even chunk this device holds
    b = jnp.where(even, hi, lo)  # the odd one
    perm_a = [
        (s, ((s if s % 2 == 0 else n_chunks - 1 - s) // 2))
        for s in range(n_dev)
    ]
    perm_b = [
        (s, (((n_chunks - 1 - s) if s % 2 == 0 else s) // 2))
        for s in range(n_dev)
    ]
    arr_a = lax.ppermute(a, axis_name, perm_a)  # chunk 2d -> h0
    arr_b = lax.ppermute(b, axis_name, perm_b)  # chunk 2d+1 -> h1
    return jnp.concatenate([arr_a, arr_b], axis=seq_axis)


def _zigzag_ring_diff(q, k, v, *, mesh, axis_name, batch_axis, head_axis,
                      scale, block_sizes, softcap, window, sinks=None,
                      segment_ids=None, max_mode="bound"):
    """Differentiable zigzag ring: in-shard_map layout exchange ->
    _zig_diff -> inverse exchange (all collective-based; autodiff
    transposes the ppermutes).  Segment ids ride replicated in GLOBAL
    order — they never enter the exchange (chunk calls slice by chunk
    id; segment matching is positionless)."""
    n_dev = mesh.shape[axis_name]
    q, k, v, chunk, n, m, c_pad, seq_axis = _zig_prepare(q, k, v, n_dev)

    from attention_tpu.parallel.cp import _maybe_axis

    h_axis = _maybe_axis(mesh, head_axis, q.shape[-3])
    if h_axis is not None and k.shape[-3] % mesh.shape[h_axis] != 0:
        h_axis = None
    if q.ndim == 4:
        b_axis = _maybe_axis(mesh, batch_axis, q.shape[0])
        seq_spec = P(b_axis, h_axis, axis_name, None)
    else:
        seq_spec = P(h_axis, axis_name, None)

    if sinks is not None and sinks > chunk:
        raise ValueError(
            f"sinks ({sinks}) must fit in one zigzag chunk ({chunk} rows)"
        )
    zcfg = _ZigCfg(
        axis_name=axis_name, n_dev=n_dev, n=n, chunk=chunk, scale=scale,
        block_sizes=block_sizes, softcap=softcap, window=window,
        sinks=sinks, max_mode=max_mode,
    )

    in_specs = [seq_spec, seq_spec, seq_spec]
    extra = []
    if segment_ids is not None:
        extra = list(_zig_pad_ids(segment_ids, m, n, c_pad))
        in_specs += [P(), P()]

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=tuple(in_specs),
        out_specs=seq_spec,
    )
    def run(q_local, k_local, v_local, *seg_local):
        exch = functools.partial(_zigzag_exchange, axis_name=axis_name,
                                 n_dev=n_dev, chunk=chunk)
        q_z, k_z, v_z = exch(q_local), exch(k_local), exch(v_local)
        if q_z.ndim == 4:
            # segments are 3D-only, so this arm never carries them
            bq, h, mm, d = q_z.shape
            bk, hkv, nn, dk_ = k_z.shape
            out = _zig_diff(
                q_z.reshape(bq * h, mm, d),
                k_z.reshape(bk * hkv, nn, dk_),
                v_z.reshape(bk * hkv, nn, v_z.shape[-1]),
                zcfg,
            )
            out = out.reshape(bq, h, mm, -1)
        else:
            out = _zig_diff(q_z, k_z, v_z, zcfg, *seg_local)
        return exch(out, inverse=True)

    out = run(q, k, v, *extra)
    if c_pad != n:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out
