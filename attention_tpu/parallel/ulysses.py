"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

The alternative context-parallel mode from SURVEY §2's strategy inventory
(not present in the reference, which is allreduce-based): instead of
rotating KV shards, a single ``lax.all_to_all`` converts
sequence-sharding into head-sharding, each device runs *complete*
attention for its subset of heads (no softmax collectives at all), and a
second all-to-all converts back.  Two collectives total per call — cheaper
than a ring when the head count divides the mesh and sequences are only
moderately long.

GQA handling: when the mesh size does not divide the KV head count,
KV heads are repeated just enough to make the reshard uniform —
normally up to the MESH size (the 32Q/4KV BASELINE config on an 8-chip
mesh repeats 2x), falling back to the full Q head count only for
ratios that divide neither way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.ops.flash import BlockSizes
from attention_tpu.ops.flash_vjp import flash_attention_diff
from attention_tpu.parallel.mesh import default_mesh, shard_map


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "batch_axis", "scale",
                     "block_sizes", "causal", "softcap", "window", "sinks",
                     "max_mode"),
)
def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    batch_axis: str | None = "dp",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    causal: bool = False,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    q_segment_ids=None,
    kv_segment_ids=None,
    max_mode: str = "bound",
) -> jax.Array:
    """All-to-all sequence-parallel attention for multi-head inputs.

    Shapes: (h, m, d) or (b, h, m, d); the sequence axes are sharded over
    ``axis_name`` on the way in and out (4D batches may additionally
    shard over ``batch_axis`` when the mesh has it and it divides).
    Requires the Q head count to be a multiple of the mesh size and
    sequence lengths to be multiples of the mesh size.

    Differentiable end to end: the inner kernel is the flash custom VJP
    and both all-to-alls (plus the GQA repeat) are transposed by
    autodiff — the backward is two more all-to-alls around the Pallas
    backward kernels, so ``cp_impl="ulysses"`` trains
    (`models/attention_layer.py`).

    Carries the single-device kernel's full masking surface (the
    reference's orchestrator supports its kernel's entire surface,
    `attention-mpi.c:191-407`): ``window``/``sinks`` (sliding window +
    StreamingLLM sinks) and packed-sequence segment ids.  After the
    head/seq all-to-all each device holds the FULL sequence for its
    head subset, so the absolute-position features apply unchanged;
    segment ids ((m,)/(n,) global, 3D inputs only — the kernel's
    limit) ride into the shard_map as replicated closures.
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    if q.ndim not in (3, 4):
        raise ValueError(f"ulysses needs (h, m, d) or (b, h, m, d); got {q.shape}")
    hq = q.shape[-3]
    hkv = k.shape[-3]
    if hq % n_dev != 0:
        raise ValueError(f"q heads {hq} not divisible by mesh size {n_dev}")
    if q.shape[-2] % n_dev != 0 or k.shape[-2] % n_dev != 0:
        raise ValueError(
            f"sequence lengths {q.shape[-2]}/{k.shape[-2]} not divisible by "
            f"mesh size {n_dev}"
        )
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    # GQA survives the all-to-all untouched iff the mesh size divides
    # the KV head count (each device then holds whole kv heads and the
    # contiguous q chunks stay group-aligned).  Otherwise the minimal
    # fix is repeating KV
    # heads up to the MESH size, not the Q head count: device r then
    # holds q heads [r·hq/R, (r+1)·hq/R) and expanded kv head r, whose
    # original head is r//(R/hkv) == (r·hq/R)//(hq/hkv) — the exact head
    # that q-chunk needs.  For 32q/4kv on 8 chips this moves 2x the KV
    # rows over the wire instead of 8x.  Ratios that divide neither way
    # fall back to the full repeat.
    if hkv != hq and hkv % n_dev != 0:
        if hq % hkv != 0:
            raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
        expand = n_dev // hkv if n_dev % hkv == 0 else hq // hkv
        k = jnp.repeat(k, expand, axis=-3)
        v = jnp.repeat(v, expand, axis=-3)

    head_axis = q.ndim - 3
    seq_axis = q.ndim - 2
    if q.ndim == 4:
        from attention_tpu.parallel.cp import _maybe_axis

        b_axis = _maybe_axis(mesh, batch_axis, q.shape[0])
        seq_spec = P(b_axis, None, axis_name, None)
    else:
        seq_spec = P(None, axis_name, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
    )
    def run(q_local, k_local, v_local):
        # seq-sharded -> head-sharded: split heads across devices, gather seq
        qh = lax.all_to_all(q_local, axis_name, head_axis, seq_axis, tiled=True)
        kh = lax.all_to_all(k_local, axis_name, head_axis, seq_axis, tiled=True)
        vh = lax.all_to_all(v_local, axis_name, head_axis, seq_axis, tiled=True)
        out = flash_attention_diff(
            qh, kh, vh, scale=scale, block_sizes=block_sizes, causal=causal,
            softcap=softcap, window=window, sinks=sinks,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            max_mode=max_mode,
        )
        # head-sharded -> seq-sharded
        return lax.all_to_all(out, axis_name, seq_axis, head_axis, tiled=True)

    return run(q, k, v)
