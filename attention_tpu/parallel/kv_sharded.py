"""KV-sharded distributed attention with two-phase softmax normalization.

The TPU-native rebuild of the reference's core distributed algorithm
(`attention-mpi.c:191-407`, SURVEY §3.3):

  * KV rows block-sharded over ranks (owner partitioner,
    `attention-mpi.c:19-27`)           → ``PartitionSpec(axis)`` on K/V
    over a 1D mesh, Q replicated;
  * each rank's local online-softmax pass producing (contrib, lmax, lsum)
    (`attention-mpi.c:333-338`)        → :func:`flash_attention_partials`
    per device inside ``shard_map``;
  * phase 1 ``MPI_Iallreduce(lmax, MAX)`` + rescale by exp(lmax-gmax)
    (`attention-mpi.c:342-351`)        → ``lax.pmax`` over the mesh axis;
  * phase 2 ``MPI_Iallreduce(lsum, SUM)`` + 1/gsum normalize
    (`attention-mpi.c:354-362`)        → ``lax.psum``;
  * ``MPI_Ireduce(contrib → root, SUM)`` (`attention-mpi.c:380`)
                                       → ``lax.psum`` of the normalized
    contributions (all-reduce rather than reduce-to-root: every chip gets
    the result, which is what a fully-sharded consumer wants; XLA lowers
    it to the same ICI reduction tree).

The reference's Q ping-pong broadcast pipeline (`attention-mpi.c:268-330`)
has no hand-written analog: Q is replicated by sharding annotation, XLA's
latency-hiding scheduler overlaps collectives with compute, and the flash
kernel's Q-block grid dimension already streams queries through VMEM in
tiles (the B=512-row batching of `attention-mpi.c:200`, done on-chip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.ops.flash import BlockSizes, flash_attention_partials
from attention_tpu.ops.reference import attention_xla_partials
from attention_tpu.parallel.mesh import default_mesh, shard_map

NEG_INF = float("-inf")


def merge_partials(out_un, lmax, lsum, axis_name: str):
    """Two-phase global softmax merge over a mesh axis.

    Inputs are each device's (contrib, row_max, row_sumexp); returns the
    globally normalized output on every device.  This is exactly steps 2-4
    of SURVEY §3.3 (reference `attention-mpi.c:340-380`).
    """
    gmax = lax.pmax(lmax, axis_name)  # phase 1: MAX allreduce
    corr = jnp.where(lmax == NEG_INF, 0.0, jnp.exp(lmax - gmax))
    gsum = lax.psum(lsum * corr, axis_name)  # phase 2: SUM allreduce
    contrib = out_un * corr[..., None]
    total = lax.psum(contrib, axis_name)  # contribution reduction
    gsum_safe = jnp.where(gsum == 0.0, 1.0, gsum)  # div-by-zero guard (:358-362)
    return total / gsum_safe[..., None]


def _local_partials(
    q, k, v, *, impl, scale, block_sizes, kv_valid, causal=False, q_offset=0,
    kv_offset=0, softcap=None, window=None, sinks=None, q_segment_ids=None,
    kv_segment_ids=None, max_mode="online",
):
    # ``max_mode`` reaches the flash kernel only: the xla impl is the
    # fp32 oracle whose exact max IS the online recurrence (bound is a
    # kernel optimization, not a semantics change — same outputs)
    if impl == "flash":
        return flash_attention_partials(
            q, k, v, scale=scale, block_sizes=block_sizes, kv_valid=kv_valid,
            causal=causal, q_offset=q_offset, kv_offset=kv_offset,
            softcap=softcap, window=window, sinks=sinks,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            max_mode=max_mode,
        )
    if window is not None or sinks is not None or q_segment_ids is not None:
        raise ValueError(
            "window/sinks/segment ids on the sharded paths run the fused "
            "kernel (impl='flash'); the xla partials oracle does not carry "
            "them"
        )
    return attention_xla_partials(
        q, k, v, scale=scale, kv_valid=kv_valid, causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, softcap=softcap,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "axis_name",
        "scale",
        "block_sizes",
        "impl",
        "causal",
        "softcap",
        "window",
        "sinks",
        "max_mode",
    ),
)
def kv_sharded_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "kv",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    impl: str = "flash",
    causal: bool = False,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
    q_segment_ids=None,
    kv_segment_ids=None,
    max_mode: str = "bound",
) -> jax.Array:
    """Distributed attention with K/V rows sharded over a 1D mesh.

    Q is replicated (broadcast role, `attention-mpi.c:232-241`); K/V rows
    are sharded (scatter role, `:242-266`); softmax is made shard-invariant
    by the two-phase pmax/psum merge.  Output is replicated on every chip.

    Accepts the same 2D/3D/4D shapes as :func:`flash_attention`; the
    sequence axis (second-to-last) of K/V is the sharded one.

    The kernel's full masking surface flows through (the reference's
    orchestrator carries its kernel's entire surface,
    `attention-mpi.c:191-407`): ``window``/``sinks`` masks are expressed
    in GLOBAL positions via each shard's dynamic ``kv_offset``, so a
    band crossing shard boundaries and the absolute sink prefix both
    resolve correctly per shard; packed-sequence segment ids ship with
    their data — Q ids replicated, KV ids sharded alongside K/V rows
    (ids must be 1D, 2D/3D inputs — the kernel's segment limit).
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    n = k.shape[-2]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    segmented = q_segment_ids is not None
    if segmented != (kv_segment_ids is not None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")

    # Pad n up to a multiple of the mesh size; each shard masks its own
    # padded tail via the dynamic kv_valid scalar.
    n_pad = -(-n // n_dev) * n_dev
    if n_pad != n:
        pad = [(0, 0)] * (k.ndim - 2) + [(0, n_pad - n), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    n_local = n_pad // n_dev

    seq_axis = k.ndim - 2
    kv_spec = P(*([None] * seq_axis), axis_name, None)
    in_specs = [P(), kv_spec, kv_spec]
    extra = []
    if segmented:
        kv_seg = jnp.asarray(kv_segment_ids, jnp.int32)
        if n_pad != n:
            # padded rows get id -1: matches no real (non-negative) id
            kv_seg = jnp.pad(kv_seg, (0, n_pad - n), constant_values=-1)
        extra = [jnp.asarray(q_segment_ids, jnp.int32), kv_seg]
        in_specs += [P(), P(axis_name)]

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=tuple(in_specs),
        out_specs=P(),
    )
    def run(q_full, k_local, v_local, *seg_local):
        idx = lax.axis_index(axis_name)
        # valid rows in this shard of the padded sequence (owner_count
        # analog: every shard owns n_local rows, the last ones partly pad)
        kv_valid = jnp.clip(n - idx * n_local, 0, n_local)
        out_un, lmax, lsum = _local_partials(
            q_full,
            k_local,
            v_local,
            impl=impl,
            scale=scale,
            block_sizes=block_sizes,
            kv_valid=kv_valid,
            causal=causal,
            kv_offset=idx * n_local,
            softcap=softcap,
            window=window,
            sinks=sinks,
            q_segment_ids=seg_local[0] if seg_local else None,
            kv_segment_ids=seg_local[1] if seg_local else None,
            max_mode=max_mode,
        )
        return merge_partials(out_un, lmax, lsum, axis_name).astype(q_full.dtype)

    return run(q, k, v, *extra)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "block_sizes", "causal",
                     "softcap", "max_mode"),
)
def q_sharded_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None = None,
    axis_name: str = "kv",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    causal: bool = False,
    softcap: float | None = None,
    max_mode: str = "bound",
) -> jax.Array:
    """Replicated-KV attention with Q rows sharded — the 'replicate' arm of
    the adaptive placement policy (small KV, `attention-mpi.c:217-241`).

    Each chip runs the fused kernel on its Q slice against the full K/V;
    there are no per-batch collectives at all.  Output is Q-sharded.
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    m = q.shape[-2]
    m_pad = -(-m // n_dev) * n_dev
    if m_pad != m:
        pad = [(0, 0)] * (q.ndim - 2) + [(0, m_pad - m), (0, 0)]
        q = jnp.pad(q, pad)
    seq_axis = q.ndim - 2
    q_spec = P(*([None] * seq_axis), axis_name, None)

    from attention_tpu.ops.flash import flash_attention

    @functools.partial(
        shard_map, mesh=mesh, check_vma=False, in_specs=(q_spec, P(), P()), out_specs=q_spec
    )
    def run(q_local, k_full, v_full):
        m_local = q_local.shape[-2]
        q_offset = lax.axis_index(axis_name) * m_local
        return flash_attention(
            q_local, k_full, v_full, scale=scale, block_sizes=block_sizes,
            causal=causal, q_offset=q_offset, softcap=softcap,
            max_mode=max_mode,
        )

    out = run(q, k, v)
    if m_pad != m:
        out = lax.slice_in_dim(out, 0, m, axis=seq_axis)
    return out
