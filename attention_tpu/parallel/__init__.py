from attention_tpu.parallel.mesh import (  # noqa: F401
    KV_REPLICATE_THRESHOLD_BYTES,
    choose_kv_placement,
    default_mesh,
)
from attention_tpu.parallel.cp import cp_flash_attention  # noqa: F401
from attention_tpu.parallel.kv_sharded import (  # noqa: F401
    kv_sharded_attention,
    q_sharded_attention,
)
from attention_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from attention_tpu.parallel.ring import (  # noqa: F401
    ring_attention,
    ring_attention_diff,
)
from attention_tpu.parallel.serving import (  # noqa: F401
    MeshConfigError,
    cache_sharded_decode,
    head_sharded_decode,
    head_sharded_decode_paged,
    head_sharded_decode_quantized,
    head_sharded_prefill,
    head_sharded_ragged_step,
)
from attention_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
