"""Sharded autoregressive decoding: serve a KV cache across a mesh.

The serving-side counterpart of the training-time parallel strategies —
not in the reference (whose kernel is one-shot batch, `attention-mpi.c`),
but required for the framework's decode path (`ops/decode.py`) to scale
the way the batch path does:

  * :func:`head_sharded_decode` — tensor-parallel serving: the KV cache
    (and the q-head groups that read it) sharded over KV heads.  Fully
    embarrassingly parallel: zero collectives per token; each chip
    streams only its own cache shard.
  * :func:`cache_sharded_decode` — sequence-parallel serving for caches
    too large for one chip's HBM: cache *rows* sharded over the mesh,
    per-shard online-softmax partials merged with the same two-phase
    pmax/psum scheme as the batch path (`kv_sharded.merge_partials`,
    the reference's `attention-mpi.c:340-380` algorithm applied to a
    single query row).
  * :func:`head_sharded_decode_quantized` / :func:`head_sharded_decode_paged`
    — the tensor-parallel layout applied to the int8 and paged cache
    types (values+scales / pools shard by KV head; page tables
    replicate), so every cache type the framework serves also serves
    sharded.
  * :func:`head_sharded_prefill` — the batch flash kernel (cached
    prefill / chunked append) under the same head sharding, so a
    ``tp_axis`` model's whole generate loop stays sharded.
  * :func:`head_sharded_ragged_step` — the serving engine's packed
    single-launch step (`ops.ragged_paged` append + attention) under
    the same head sharding: pools and new K/V rows shard by KV head,
    every host-packed index array replicates, both halves run inside
    one shard_map — ``EngineConfig.mesh_shards`` lowers onto this.

Both are `shard_map`s over a 1D mesh axis and compose with an outer
batch/data-parallel axis via pjit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from attention_tpu.ops.decode import flash_decode
from attention_tpu.ops.flash import BlockSizes, flash_attention_partials
from attention_tpu.parallel.kv_sharded import merge_partials
from attention_tpu.parallel.mesh import default_mesh, shard_map


class MeshConfigError(ValueError):
    """A sharded serving call's geometry cannot split over the mesh.

    Raised at CALL time when the KV-head count does not divide by the
    mesh-axis size (an uneven split would silently mis-slice the
    contiguous head chunk GQA groups depend on), or by the serving
    engine when ``EngineConfig.mesh_shards`` asks for more devices
    than the runtime exposes.  Subclasses ValueError so existing
    argument-validation callers keep working; typed so mesh-serving
    callers can distinguish "fix your shard count" from a kernel
    bug."""


def _head_sharded_call(q, hkv, mesh, axis_name, kernel, operands,
                       operand_specs):
    """Shared tensor-parallel scaffold for every cache type: validate
    KV-head divisibility, shard ``q`` (and whatever cache pytree
    ``operands`` carries, per ``operand_specs``) along the KV-head dim,
    and run ``kernel`` per shard.  Adding a decode option means
    threading it through ONE wrapper's kernel closure, not three copies
    of this plumbing."""
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    if hkv % n_dev:
        raise MeshConfigError(
            f"kv heads {hkv} not divisible by mesh size {n_dev}"
        )
    # q is (B, H, d) for decode, (B, H, S, d) for prefill — heads at dim 1
    q_spec = P(None, axis_name, *([None] * (q.ndim - 2)))

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=(q_spec, *operand_specs),
        out_specs=q_spec,
    )
    def run(q_local, *ops):
        return kernel(q_local, *ops)

    return run(q, *operands)


def head_sharded_prefill(q, k, v, *, mesh=None, axis_name="tp", **kw):
    """Batch flash attention (cached prefill / chunked append) with the
    heads sharded over ``axis_name`` — per-head math is independent, so
    the shard_map needs no collectives and contiguous head chunks keep
    GQA groups aligned.  ``kw`` passes straight to
    :func:`ops.flash.flash_attention`; traced scalars in it (q_offset,
    kv_valid) ride in as replicated closures.  Shapes: (B, H, S, d)."""
    from attention_tpu.ops.flash import flash_attention

    spec = P(None, axis_name, None, None)

    def kernel(q_local, k_local, v_local):
        return flash_attention(q_local, k_local, v_local, **kw)

    return _head_sharded_call(
        q, k.shape[1], mesh, axis_name, kernel, (k, v), (spec, spec),
    )


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "block_k", "interpret",
                     "softcap", "window", "sinks"),
)
def head_sharded_decode(
    q: jax.Array,        # (B, H, d)
    k_cache: jax.Array,  # (B, Hkv, N, d)
    v_cache: jax.Array,  # (B, Hkv, N, dv)
    lengths: jax.Array,  # (B,) or scalar
    *,
    mesh: Mesh | None = None,
    axis_name: str = "tp",
    scale: float | None = None,
    block_k: int = 2048,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """Tensor-parallel decode: KV heads sharded, zero collectives.

    Contiguous head chunks keep q-head -> kv-head groups aligned per
    device (q head j reads kv head j // group; chunk r holds q heads
    [r·H/R, (r+1)·H/R) and exactly their kv heads [r·Hkv/R, ...)), so
    each chip runs a complete :func:`flash_decode` on its slice.

    A 4-D ``q`` (B, H, S, d) runs the speculative-verify chunk kernel
    (:func:`ops.decode.flash_decode_chunk`) per head shard instead —
    ``lengths`` is then the post-append length.
    """
    lens = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (q.shape[0],))
    c_spec = P(None, axis_name, None, None)

    def kernel(q_local, k_local, v_local, lens_full):
        if q_local.ndim == 4:
            from attention_tpu.ops.decode import flash_decode_chunk

            return flash_decode_chunk(
                q_local, k_local, v_local, lens_full,
                scale=scale, block_k=block_k, interpret=interpret,
                softcap=softcap, window=window, sinks=sinks,
            )
        return flash_decode(
            q_local, k_local, v_local, lens_full,
            scale=scale, block_k=block_k, interpret=interpret,
            softcap=softcap, window=window, sinks=sinks,
        )

    return _head_sharded_call(
        q, k_cache.shape[1], mesh, axis_name, kernel,
        (k_cache, v_cache, lens), (c_spec, c_spec, P(None)),
    )


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "block_k", "interpret",
                     "softcap", "window", "sinks"),
)
def head_sharded_decode_quantized(
    q: jax.Array,  # (B, H, d)
    cache,         # ops.quant.QuantizedKV (int8 values + fp32 scales)
    lengths: jax.Array,  # (B,) or scalar
    *,
    mesh: Mesh | None = None,
    axis_name: str = "tp",
    scale: float | None = None,
    block_k: int = 4096,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """Tensor-parallel decode against an int8 KV cache.

    The same contiguous-head-chunk layout as :func:`head_sharded_decode`
    applied to every field of the ``QuantizedKV`` pytree (values AND
    their sublane-replicated scales shard along the KV-head dim), so
    each chip runs a complete :func:`flash_decode_quantized` on its
    slice — zero collectives per token, at 0.63x the per-chip cache HBM
    of the bf16 path.  ``window``/``sinks`` serve sliding-window models
    through the same sharding.
    """
    from attention_tpu.ops.quant import QuantizedKV, flash_decode_quantized

    lens = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (q.shape[0],))
    f_spec = P(None, axis_name, None, None)  # every field: (B, Hkv, ...)
    cache_specs = QuantizedKV(f_spec, f_spec, f_spec, f_spec)

    def kernel(q_local, cache_local, lens_full):
        if q_local.ndim == 4:  # speculative-verify chunk (see
            # head_sharded_decode): per-shard chunk kernel, same layout
            from attention_tpu.ops.quant import (
                flash_decode_quantized_chunk,
            )

            return flash_decode_quantized_chunk(
                q_local, cache_local, lens_full,
                scale=scale, block_k=block_k, interpret=interpret,
                softcap=softcap, window=window, sinks=sinks,
            )
        return flash_decode_quantized(
            q_local, cache_local, lens_full,
            scale=scale, block_k=block_k, interpret=interpret,
            softcap=softcap, window=window, sinks=sinks,
        )

    return _head_sharded_call(
        q, cache.k_q.shape[1], mesh, axis_name, kernel,
        (cache, lens), (cache_specs, P(None)),
    )


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "interpret", "softcap",
                     "window", "sinks"),
)
def head_sharded_decode_paged(
    q: jax.Array,  # (B, H, d)
    cache,         # ops.paged.PagedKV (pools + page table + lengths)
    *,
    mesh: Mesh | None = None,
    axis_name: str = "tp",
    scale: float | None = None,
    interpret: bool | None = None,
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
) -> jax.Array:
    """Tensor-parallel decode through a paged KV pool.

    The physical pools (P, Hkv, page_size, d) shard along their KV-head
    dim; the page table and lengths replicate (page ids are head-
    agnostic), so each chip translates the same logical pages into its
    own head slice of the pool and runs a complete
    :func:`paged_flash_decode` — zero collectives per token.  A serving
    stack can therefore combine prefix sharing (forked page tables) with
    tensor parallelism without resharding the pool.
    """
    from attention_tpu.ops.paged import PagedKV, paged_flash_decode

    pool_spec = P(None, axis_name, None, None)
    cache_specs = PagedKV(pool_spec, pool_spec, P(None, None), P(None))

    def kernel(q_local, cache_local):
        return paged_flash_decode(
            q_local, cache_local,
            scale=scale, interpret=interpret,
            softcap=softcap, window=window, sinks=sinks,
        )

    return _head_sharded_call(
        q, cache.k_pool.shape[1], mesh, axis_name, kernel,
        (cache,), (cache_specs,),
    )


def head_sharded_ragged_step(
    q: jax.Array,      # (1, Hq, T, d) packed token axis
    cache,             # ops.ragged_paged.RaggedPagedStep
    k_new: jax.Array,  # (1, Hkv, T, d) this step's new K rows
    v_new: jax.Array,  # (1, Hkv, T, d)
    *,
    mesh: Mesh | None = None,
    axis_name: str = "tp",
    softcap: float | None = None,
    window: int | None = None,
    sinks: int | None = None,
):
    """The packed serving step (append + ragged attention) with KV
    heads sharded over ``axis_name`` — the engine's single-launch
    lowering made tensor-parallel.

    Both halves of the step run INSIDE one shard_map so the pool
    scatter and the attention read stay a single per-shard program:
    the physical pools (P, Hkv, page_size, d) and this step's new K/V
    rows shard along their KV-head dim, while every host-packed index
    array — page tables, ``kv_lens``, ``cu_q_lens``, the decode/
    prefill ``distribution``, per-token position/slot, the ``q_span``
    tile marker — replicates (page ids and packing are head-agnostic).
    Contiguous head chunks keep GQA groups aligned per shard (the
    `head_sharded_decode` layout), so each device appends to and
    scores only its own head slice: zero collectives per step.  The
    post-append ``kv_lens`` is recomputed identically on every shard
    from replicated inputs, so the returned cache's replicated
    out-spec is exact, not approximate.

    Returns ``(out, cache)`` exactly like the single-device
    ``ragged_paged_append`` + ``ragged_paged_attention`` pair.
    """
    from attention_tpu.ops.ragged_paged import (
        RaggedPagedStep,
        ragged_paged_append,
        ragged_paged_attention,
    )

    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    hkv = cache.k_pool.shape[1]
    if hkv % n_dev:
        raise MeshConfigError(
            f"kv heads {hkv} not divisible by mesh size {n_dev}"
        )
    if q.shape[1] % n_dev:
        raise MeshConfigError(
            f"q heads {q.shape[1]} not divisible by mesh size {n_dev}"
        )
    head_spec = P(None, axis_name, None, None)
    rep1 = P(None)
    cache_specs = RaggedPagedStep(
        k_pool=head_spec, v_pool=head_spec,
        page_table=P(None, None), kv_lens=rep1, cu_q_lens=rep1,
        distribution=rep1, token_pos=rep1, token_slot=rep1,
        q_span=rep1,
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=(head_spec, cache_specs, head_spec, head_spec),
        out_specs=(head_spec, cache_specs),
    )
    def run(q_local, cache_local, k_local, v_local):
        cache_local = ragged_paged_append(cache_local, k_local, v_local)
        out = ragged_paged_attention(
            q_local, cache_local, softcap=softcap, window=window,
            sinks=sinks,
        )
        return out, cache_local

    return run(q, cache, k_new, v_new)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "block_sizes",
                     "softcap"),
)
def cache_sharded_decode(
    q: jax.Array,        # (B, H, d)
    k_cache: jax.Array,  # (B, Hkv, N, d)
    v_cache: jax.Array,  # (B, Hkv, N, dv)
    length: jax.Array,   # scalar valid length (uniform batch)
    *,
    mesh: Mesh | None = None,
    axis_name: str = "sp",
    scale: float | None = None,
    block_sizes: BlockSizes | None = None,
    softcap: float | None = None,
) -> jax.Array:
    """Sequence-parallel decode: cache *rows* sharded over the mesh.

    Each device computes online-softmax partials over its cache shard
    (kv_valid clipped to the shard's slice of the valid prefix), then
    the two-phase pmax/psum merge normalizes globally — one query row's
    worth of the reference's distributed softmax (SURVEY §3.3).
    """
    if mesh is None:
        mesh = default_mesh(axis_name)
    n_dev = mesh.shape[axis_name]
    b, h, d = q.shape
    _, hkv, n, dv = v_cache.shape
    if n % n_dev:
        raise ValueError(
            f"cache capacity {n} not divisible by mesh size {n_dev}"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    group = h // hkv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    shard_n = n // n_dev
    length = jnp.asarray(length, jnp.int32).reshape(())

    # Each (batch, kv-head) pair becomes one kernel head whose q rows are
    # the GQA group — the same layout trick as `flash_decode`.
    qs = q.reshape(b * hkv, group, d)
    kc = k_cache.reshape(b * hkv, n, d)
    vc = v_cache.reshape(b * hkv, n, dv)

    c_spec = P(None, axis_name, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(), c_spec, c_spec, P()),
        out_specs=P(),
    )
    def run(q_full, k_local, v_local, length_full):
        idx = lax.axis_index(axis_name)
        kv_valid = jnp.clip(length_full - idx * shard_n, 0, shard_n)
        out_un, lmax, lsum = flash_attention_partials(
            q_full, k_local, v_local, scale=scale,
            block_sizes=block_sizes, kv_valid=kv_valid,
            softcap=softcap,
        )
        return merge_partials(out_un, lmax, lsum, axis_name)

    out = run(qs, kc, vc, length)  # (b*hkv, group, dv), replicated
    return out.reshape(b, h, dv).astype(v_cache.dtype)
