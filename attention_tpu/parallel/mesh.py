"""Device mesh construction and data-placement policy.

Replaces the reference's L0/L3 runtime plumbing with JAX's declarative
sharding model:

  * the owner partitioner (`attention-mpi.c:19-27`) — block-partitioning n
    KV rows over ranks with ±1-row balance — becomes a
    ``PartitionSpec('kv')`` over a 1D mesh: XLA block-partitions the
    sharded axis the same way;
  * the adaptive Bcast-vs-Scatterv distribution (`attention-mpi.c:210-266`,
    64 MB threshold at `:213-215`) becomes the replicate-vs-shard placement
    choice below.  The reference's insight — small KV is cheaper to
    broadcast than to scatter — maps to: small KV should be *replicated*
    (each chip computes its own Q rows with zero per-batch collectives),
    large KV should be *sharded* (two-phase softmax collectives over ICI);
  * UCX/OMPI env bootstrap (`attention-mpi.c:10-17`) has no analog: ICI
    transport selection is XLA's job.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

# The reference flips from Bcast (replicate-style transport) to Scatterv
# (shard-style transport) at 64 MB of fp32 KV (`attention-mpi.c:213-215`).
# We reuse the same threshold for the replicate-vs-shard placement choice;
# v5e has 16 GB HBM per chip, so replication is about HBM headroom and
# collective cost, not a hard limit.
KV_REPLICATE_THRESHOLD_BYTES = 64 * 2**20


def default_mesh(axis_name: str = "kv", devices=None) -> Mesh:
    """A 1D mesh over all local devices — the `MPI_COMM_WORLD` analog."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def hybrid_mesh(inner_axis: str = "kv", outer_axis: str = "dp") -> Mesh:
    """A 2D (outer, inner) mesh laid out so the inner axis rides ICI and
    the outer axis rides DCN — the multi-host analog of the reference's
    multi-node MPI world (nodes over ConnectX-5 fabric, ranks within a
    node over shared memory; `README.md:85-89`, process-placement study
    Q5).

    On a multi-host (multi-process) runtime this uses
    `mesh_utils.create_hybrid_device_mesh` so every inner-axis
    collective (the two-phase pmax/psum softmax, ring ppermute) stays
    on-slice; keep only low-frequency traffic (data-parallel gradient
    psum) on the outer axis.  On a single host it degrades to
    (1, n_devices) — same program, no DCN hops.
    """
    devices = jax.devices()
    n_proc = getattr(jax, "process_count", lambda: 1)()
    if n_proc > 1:
        from jax.experimental import mesh_utils

        per_proc = len(devices) // n_proc
        # result shape = mesh_shape * dcn_mesh_shape elementwise:
        # (1, per_proc) x (n_proc, 1) -> (n_proc, per_proc) matching
        # (outer_axis, inner_axis)
        # process_is_granule: DCN granules are hosts (matching n_proc),
        # not ICI slices — a multi-host single-slice pod has 1 slice but
        # n_proc hosts, and the default slice grouping would raise.
        dev_mesh = mesh_utils.create_hybrid_device_mesh(
            (1, per_proc), (n_proc, 1), devices=devices,
            process_is_granule=True,
        )
        return Mesh(dev_mesh, (outer_axis, inner_axis))
    return Mesh(np.asarray(devices).reshape(1, -1), (outer_axis, inner_axis))


def choose_kv_placement(
    n: int,
    dk: int,
    dv: int,
    *,
    itemsize: int = 4,
    threshold_bytes: int = KV_REPLICATE_THRESHOLD_BYTES,
    kv_heads: int = 1,
) -> str:
    """'replicate' or 'shard' — the adaptive distribution policy (C11).

    Mirrors the reference's ``total_kv = n*(dk+dv)*4B`` vs 64 MB test
    (`attention-mpi.c:213-215`) with the placement decision that makes
    sense on TPU: below the threshold, replicate KV on every chip and
    shard the *queries* (no per-batch collectives at all); above it,
    shard KV rows and pay the two-phase softmax collectives.
    """
    total_kv = kv_heads * n * (dk + dv) * itemsize
    return "replicate" if total_kv < threshold_bytes else "shard"
