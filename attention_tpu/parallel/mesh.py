"""Device mesh construction and data-placement policy.

Replaces the reference's L0/L3 runtime plumbing with JAX's declarative
sharding model:

  * the owner partitioner (`attention-mpi.c:19-27`) — block-partitioning n
    KV rows over ranks with ±1-row balance — becomes a
    ``PartitionSpec('kv')`` over a 1D mesh: XLA block-partitions the
    sharded axis the same way;
  * the adaptive Bcast-vs-Scatterv distribution (`attention-mpi.c:210-266`,
    64 MB threshold at `:213-215`) becomes the replicate-vs-shard placement
    choice below.  The reference's insight — small KV is cheaper to
    broadcast than to scatter — maps to: small KV should be *replicated*
    (each chip computes its own Q rows with zero per-batch collectives),
    large KV should be *sharded* (two-phase softmax collectives over ICI);
  * UCX/OMPI env bootstrap (`attention-mpi.c:10-17`) has no analog: ICI
    transport selection is XLA's job.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

# Fallback threshold for callers that cannot supply the query-side shape
# (legacy signature).  The reference flipped Bcast->Scatterv at a
# measured 64 MB (`attention-mpi.c:213-215`, report Q8) — an
# MPI-tree-topology fact, not a TPU one.  When `m` is known the decision
# below uses the fabric-independent byte model instead (see
# `choose_kv_placement`); this constant only gates the m-less path and
# is set where the byte model lands for the repo's square headline
# shapes (m == n, d = 128: crossover at n ~ 2.6k -> ~2.7 MB of fp32 KV;
# kept at the reference's 64 MB would mis-place every square shape from
# 2.6k to 32k — artifacts/placement_sweep.json).
KV_REPLICATE_THRESHOLD_BYTES = 4 * 2**20

# Allreduce-vs-broadcast byte ratio: sharding pays a two-phase merge
# (reduce-scatter + all-gather ~ 2x bytes on the wire) every call where
# replication pays a one-time (1 - 1/R) broadcast — fabric-independent
# factors (the same 2x the reference's Iallreduce pair pays over its
# Ibcast, `attention-mpi.c:342,354` vs `:305`).  Validated directionally
# on the 8-CPU mesh (scripts/placement_sweep.py).
MERGE_ALPHA = 2.0

# Replicating KV on every chip is capacity-bounded long before 16 GB
# HBM fills: leave room for Q, outputs, double buffers.
KV_REPLICATE_HBM_CAP_BYTES = 4 * 2**30


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across API generations (the compat shim every
    orchestrator in this package routes through).

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; older
    releases spell it ``check_rep`` and/or keep the function under
    ``jax.experimental.shard_map``.  One resolution point here beats
    twelve call sites drifting independently (the
    ``_compiler_params`` lesson from `ops/flash.py`)."""
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        for check_kw in ("check_vma", "check_rep"):
            try:
                if check_vma is None:
                    return sm(f, **kw)
                return sm(f, **kw, **{check_kw: check_vma})
            except TypeError:
                continue
    from jax.experimental.shard_map import shard_map as legacy_sm

    if check_vma is None:
        return legacy_sm(f, **kw)
    return legacy_sm(f, **kw, check_rep=check_vma)


def mesh_context(mesh: Mesh):
    """``with``-able mesh activation across jax API generations (the
    same one-resolution-point discipline as :func:`shard_map` above).

    Newer jax activates a mesh for PartitionSpec resolution with
    ``jax.sharding.set_mesh``; older releases don't have it — there the
    ``Mesh`` object is its own context manager, which is what
    ``with_sharding_constraint`` reads."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    return set_mesh(mesh) if set_mesh is not None else mesh


def default_mesh(axis_name: str = "kv", devices=None) -> Mesh:
    """A 1D mesh over all local devices — the `MPI_COMM_WORLD` analog."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def hybrid_mesh(inner_axis: str = "kv", outer_axis: str = "dp") -> Mesh:
    """A 2D (outer, inner) mesh laid out so the inner axis rides ICI and
    the outer axis rides DCN — the multi-host analog of the reference's
    multi-node MPI world (nodes over ConnectX-5 fabric, ranks within a
    node over shared memory; `README.md:85-89`, process-placement study
    Q5).

    On a multi-host (multi-process) runtime this uses
    `mesh_utils.create_hybrid_device_mesh` so every inner-axis
    collective (the two-phase pmax/psum softmax, ring ppermute) stays
    on-slice; keep only low-frequency traffic (data-parallel gradient
    psum) on the outer axis.  On a single host it degrades to
    (1, n_devices) — same program, no DCN hops.
    """
    devices = jax.devices()
    n_proc = getattr(jax, "process_count", lambda: 1)()
    if n_proc > 1:
        from jax.experimental import mesh_utils

        per_proc = len(devices) // n_proc
        # result shape = mesh_shape * dcn_mesh_shape elementwise:
        # (1, per_proc) x (n_proc, 1) -> (n_proc, per_proc) matching
        # (outer_axis, inner_axis)
        # process_is_granule: DCN granules are hosts (matching n_proc),
        # not ICI slices — a multi-host single-slice pod has 1 slice but
        # n_proc hosts, and the default slice grouping would raise.
        dev_mesh = mesh_utils.create_hybrid_device_mesh(
            (1, per_proc), (n_proc, 1), devices=devices,
            process_is_granule=True,
        )
        return Mesh(dev_mesh, (outer_axis, inner_axis))
    return Mesh(np.asarray(devices).reshape(1, -1), (outer_axis, inner_axis))


def choose_kv_placement(
    n: int,
    dk: int,
    dv: int,
    *,
    itemsize: int = 4,
    threshold_bytes: int = KV_REPLICATE_THRESHOLD_BYTES,
    kv_heads: int = 1,
    m: int | None = None,
    q_heads: int | None = None,
    n_devices: int | None = None,
) -> str:
    """'replicate' or 'shard' — the adaptive distribution policy (C11),
    re-derived for TPU (round 5).

    The reference compared KV size against a measured 64 MB Bcast/
    Scatterv flip (`attention-mpi.c:213-215`) — a property of MPI's
    pre-built broadcast tree.  On a TPU mesh both placements execute
    identical FLOPs; what differs is bytes moved:

      * replicate KV / shard Q: a one-time (1 - 1/R) broadcast of the
        full KV, then ZERO per-call collectives (outputs are already
        Q-sharded);
      * shard KV rows: 1/R of the KV moves, but every call pays the
        two-phase merge — pmax/psum of the (h, m) stats and a psum of
        the (h, m, dv) fp32 contribs, ~2x those bytes on the wire
        (reduce-scatter + all-gather).

    So with the query side known the decision is a byte RATIO (m
    against n), not an absolute KV size: replicate iff
    ``(1 - 1/R) * kv_bytes < MERGE_ALPHA * merge_bytes``, capacity-
    capped by per-chip HBM headroom.  Validated on the 8-CPU mesh
    (scripts/placement_sweep.py -> artifacts/placement_sweep.json).
    Callers that cannot supply ``m`` fall back to the bytes threshold
    (now set where the model lands for square shapes, not at MPI's
    64 MB).
    """
    total_kv = kv_heads * n * (dk + dv) * itemsize
    if total_kv > KV_REPLICATE_HBM_CAP_BYTES:
        return "shard"  # capacity-forced regardless of comm optimum
    if m is None:
        return "replicate" if total_kv < threshold_bytes else "shard"
    if n_devices is None:
        n_devices = max(len(jax.devices()), 1)
    bcast_bytes = (1.0 - 1.0 / n_devices) * total_kv
    # stats ride lane-replicated fp32 (2 vectors) + fp32 contribs
    merge_bytes = (q_heads or kv_heads) * m * (dv + 2) * 4
    return ("replicate"
            if bcast_bytes < MERGE_ALPHA * merge_bytes else "shard")
