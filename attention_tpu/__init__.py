"""attention-tpu: a TPU-native scaled-dot-product-attention framework.

A brand-new JAX/XLA/Pallas/pjit framework with the capabilities of the
MPI/AVX-512 reference (`attention.c` / `attention-mpi.c`):

- ``core``      — problem definition, fp64 serial oracle, binary testcase
                  format + generator + verifier (reference `attention.c:84-162`).
- ``ops``       — compute kernels: XLA reference implementation and a fused
                  Pallas flash-attention kernel (replaces the reference's
                  AVX-512 kernels, `attention-mpi.c:103-189`).
- ``parallel``  — device-mesh distribution: KV-sharded attention with the
                  two-phase max/sum softmax normalization
                  (`attention-mpi.c:340-362`), ring attention for long
                  context, and Ulysses all-to-all head/sequence parallelism.
- ``models``    — multi-head / grouped-query attention modules and a small
                  transformer stack used for end-to-end training tests.
- ``utils``     — timing, FLOPs accounting, config.
- ``cli``       — `attention-tpu <testcase.bin> --backend=...`, preserving
                  the reference's CLI harness contract
                  (`attention.c:164-196`).

The public API mirrors the reference's single entry point
``attention(Q, K, V) -> result`` (`attention.c:20-21`) with a backend
registry replacing the serial/MPI source-file split.
"""

__version__ = "0.1.0"

from attention_tpu.api import attention, available_backends  # noqa: F401
