"""Metric/span naming convention: ``layer.component.verb``.

One flat, predictable namespace: lowercase dot-separated segments
(``[a-z][a-z0-9_]*``), two to four of them — ``engine.step``,
``engine.scheduler.admit``, ``ops.flash.calls``.  The registry rejects
malformed names at creation time (so a typo dies at the first call
site, not in a dashboard), and ``scripts/check_obs_names.py`` lints
every literal name in the tree against the same predicate, the
`check_shipped_table.py` discipline applied to telemetry.
"""

from __future__ import annotations

import re

_SEGMENT = r"[a-z][a-z0-9_]*"
NAME_RE = re.compile(rf"^{_SEGMENT}(\.{_SEGMENT}){{1,3}}$")

#: label keys are single segments (no dots)
LABEL_RE = re.compile(rf"^{_SEGMENT}$")


def check_name(name: str) -> bool:
    """True iff ``name`` follows the convention."""
    return bool(NAME_RE.match(name))


def require_name(name: str) -> str:
    """``name``, or ValueError describing the convention."""
    if not check_name(name):
        raise ValueError(
            f"telemetry name {name!r} violates the naming convention: "
            "2-4 lowercase dot-separated segments matching "
            "[a-z][a-z0-9_]* (layer.component.verb), e.g. 'engine.step' "
            "or 'ops.flash.calls'"
        )
    return name


def prom_name(name: str, *, kind: str = "") -> str:
    """Prometheus spelling: dots become underscores; counters gain the
    conventional ``_total`` suffix."""
    flat = name.replace(".", "_")
    if kind == "counter" and not flat.endswith("_total"):
        flat += "_total"
    return flat
