"""Metric/span naming convention: ``layer.component.verb``.

One flat, predictable namespace: lowercase dot-separated segments
(``[a-z][a-z0-9_]*``), two to four of them — ``engine.step``,
``engine.scheduler.admit``, ``ops.flash.calls``.  The registry rejects
malformed names at creation time (so a typo dies at the first call
site, not in a dashboard), and ``scripts/check_obs_names.py`` lints
every literal name in the tree against the same predicate, the
`check_shipped_table.py` discipline applied to telemetry.
"""

from __future__ import annotations

import re

_SEGMENT = r"[a-z][a-z0-9_]*"
NAME_RE = re.compile(rf"^{_SEGMENT}(\.{_SEGMENT}){{1,3}}$")

#: label keys are single segments (no dots)
LABEL_RE = re.compile(rf"^{_SEGMENT}$")


def check_name(name: str) -> bool:
    """True iff ``name`` follows the convention."""
    return bool(NAME_RE.match(name))


def require_name(name: str) -> str:
    """``name``, or ValueError describing the convention."""
    if not check_name(name):
        raise ValueError(
            f"telemetry name {name!r} violates the naming convention: "
            "2-4 lowercase dot-separated segments matching "
            "[a-z][a-z0-9_]* (layer.component.verb), e.g. 'engine.step' "
            "or 'ops.flash.calls'"
        )
    return name


def prom_name(name: str, *, kind: str = "") -> str:
    """Prometheus spelling: dots become underscores; counters gain the
    conventional ``_total`` suffix."""
    flat = name.replace(".", "_")
    if kind == "counter" and not flat.endswith("_total"):
        flat += "_total"
    return flat


# -- request-trace event names (closed enum) ------------------------------
#
# A RequestTrace chain is built ONLY from these event types; the trace
# recorder rejects anything else at record time and the obs-naming lint
# (ATP504) rejects unknown literals at review time.  The set is a
# contract: the chaos `trace_completeness` invariant and the journey
# report both reason structurally about these names.

#: events that end a chain — every well-formed chain has exactly one,
#: as its last event
TRACE_TERMINAL_EVENTS = frozenset({
    "finished", "timed_out", "shed", "cancelled",
})

#: the full closed enum of trace event types
TRACE_EVENTS = frozenset({
    "submitted",      # frontend accepted the request (chain start)
    "routed",         # router chose a replica
    "admitted",       # replica engine accepted the request
    "prefill_start",  # scheduler first put the request on a step
    "first_token",    # first output token emitted (TTFT mark)
    "preempted",      # scheduler evicted the request mid-flight
    "resumed",        # request re-entered a step after preempt/handoff
    "migrated",       # drained source -> dest (cancel-before-admit)
    "retried",        # requeued with backoff after replica death
    "warm_adopted",   # in-flight stream adopted across a warm restart
}) | TRACE_TERMINAL_EVENTS


def check_event(event: str) -> bool:
    """True iff ``event`` is a known trace event type."""
    return event in TRACE_EVENTS


def require_event(event: str) -> str:
    """``event``, or ValueError naming the closed enum."""
    if event not in TRACE_EVENTS:
        raise ValueError(
            f"unknown trace event {event!r}; trace chains are built from "
            f"the closed enum in obs/naming.py: "
            f"{', '.join(sorted(TRACE_EVENTS))}"
        )
    return event


# -- flight-recorder event kinds (closed enum) ----------------------------
#
# The blackbox ring (obs/blackbox.py) is built ONLY from these typed
# causal-event kinds; the recorder rejects anything else at note time
# and the obs-naming lint (ATP507) rejects unknown literals at review
# time.  Like TRACE_EVENTS, the set is a contract: the chaos
# `incident_completeness` invariant and the postmortem timeline both
# reason structurally about these names.

#: the full closed enum of flight-recorder event kinds
BLACKBOX_EVENTS = frozenset({
    "route_decision",    # router chose (or refused) a replica
    "shed",              # request shed on watermark/pressure/deadline
    "lease_grant",       # prefill lease acquired by a leader
    "lease_expire",      # prefill lease expired / torn from a dead leader
    "store_import",      # prefix-store chain imported at admission
    "store_evict",       # prefix-store record evicted (TTL/LRU/budget)
    "store_corrupt",     # prefix-store record failed CRC, typed error
    "replica_kill",      # replica killed (chaos or supervisor verdict)
    "replica_restart",   # replica restarted (warm or cold)
    "replica_migrate",   # in-flight request drained source -> dest
    "standby_promote",   # warm standby promoted into the serving set
    "fault_injected",    # chaos fault armed/fired by an injector
    "anomaly_fire",      # an online detector crossed its pinned bound
    "incident_dump",     # a postmortem bundle was written
    "scale_up",          # autoscaler promoted a standby into a pool
    "scale_down",        # autoscaler drained + demoted a pool member
    "handoff",           # prefill->decode cut shipped committed KV pages
    "handoff_fallback",  # handoff payload refused, typed + re-prefill
    "actuation_veto",    # anomaly firing blocked a pending scale-down
})


def check_blackbox_event(kind: str) -> bool:
    """True iff ``kind`` is a known flight-recorder event kind."""
    return kind in BLACKBOX_EVENTS


def require_blackbox_event(kind: str) -> str:
    """``kind``, or ValueError naming the closed enum."""
    if kind not in BLACKBOX_EVENTS:
        raise ValueError(
            f"unknown blackbox event {kind!r}; the flight recorder is "
            f"built from the closed enum in obs/naming.py: "
            f"{', '.join(sorted(BLACKBOX_EVENTS))}"
        )
    return kind


# -- anomaly detector names (closed enum) ----------------------------------

#: the online detectors obs/anomaly.py may run — firing records and the
#: anomaly gauges are labeled ONLY with these names
ANOMALY_DETECTORS = frozenset({
    "residual_band",   # forecaster one-step residual outside its band
    "burn_slope",      # SLO burn rate rising across adjacent windows
    "gray_failure",    # replica latency diverged from its peers' merge
})


def require_detector(name: str) -> str:
    """``name``, or ValueError naming the closed enum."""
    if name not in ANOMALY_DETECTORS:
        raise ValueError(
            f"unknown anomaly detector {name!r}; detectors are the "
            f"closed enum in obs/naming.py: "
            f"{', '.join(sorted(ANOMALY_DETECTORS))}"
        )
    return name


# -- frozen fleet series names --------------------------------------------
#
# The digest/SLO surface below is the INPUT CONTRACT for the planned
# load forecaster and SLO-aware admission (ROADMAP): renaming any of
# these is a breaking change to downstream consumers.  All latency
# digests are tick/step-denominated (never wall time) so fleet rollups
# stay deterministic.

#: per-replica TTFT digest, ticks, labels: replica, tenant, priority
SERIES_TTFT_DIGEST = "frontend.digest.ttft_ticks"
#: per-replica TPOT digest, ticks/token, labels: replica, tenant, priority
SERIES_TPOT_DIGEST = "frontend.digest.tpot_ticks"
#: engine-local TTFT digest, steps, single-engine serve path
SERIES_ENGINE_TTFT_DIGEST = "engine.digest.ttft_steps"
#: engine-local TPOT digest, steps/token
SERIES_ENGINE_TPOT_DIGEST = "engine.digest.tpot_steps"
#: SLO burn rate gauge, labels: objective, tenant, priority
SERIES_SLO_BURN_RATE = "frontend.slo.burn_rate"
#: SLO error-budget remaining gauge (1.0 = untouched), same labels
SERIES_SLO_BUDGET = "frontend.slo.budget_remaining"
#: SLO violation counter, same labels
SERIES_SLO_VIOLATIONS = "frontend.slo.violations"
#: horizon-h forecast of mean fleet pressure, labels: horizon
SERIES_FORECAST_PRESSURE = "frontend.forecast.pressure"
#: fleet headroom gauge (1.0 = fully idle, 0.0 = saturated)
SERIES_CAPACITY_HEADROOM = "frontend.capacity.headroom"
#: cost-per-token gauge, replica-ticks spent per emitted token
SERIES_COST_PER_TOKEN = "obs.capacity.cost_per_token"
#: latest one-step forecaster residual vs its p90 band, labels: none
SERIES_ANOMALY_RESIDUAL = "frontend.anomaly.residual"
#: SLO burn-rate slope across adjacent windows, labels: objective
SERIES_ANOMALY_BURN_SLOPE = "frontend.anomaly.burn_slope"
#: per-replica gray-failure score (latency vs peer merge), labels: replica
SERIES_ANOMALY_GRAY_SCORE = "frontend.anomaly.gray_score"
#: detector firing counter, labels: detector
SERIES_ANOMALY_FIRINGS = "frontend.anomaly.firings"

#: every frozen fleet series, name -> instrument kind
FROZEN_SERIES: dict[str, str] = {
    SERIES_TTFT_DIGEST: "digest",
    SERIES_TPOT_DIGEST: "digest",
    SERIES_ENGINE_TTFT_DIGEST: "digest",
    SERIES_ENGINE_TPOT_DIGEST: "digest",
    SERIES_SLO_BURN_RATE: "gauge",
    SERIES_SLO_BUDGET: "gauge",
    SERIES_SLO_VIOLATIONS: "counter",
    SERIES_FORECAST_PRESSURE: "gauge",
    SERIES_CAPACITY_HEADROOM: "gauge",
    SERIES_COST_PER_TOKEN: "gauge",
    SERIES_ANOMALY_RESIDUAL: "gauge",
    SERIES_ANOMALY_BURN_SLOPE: "gauge",
    SERIES_ANOMALY_GRAY_SCORE: "gauge",
    SERIES_ANOMALY_FIRINGS: "counter",
}
