"""Process-wide registry of typed instruments: counters, gauges,
fixed-bucket histograms.

Every instrument is a named family of labeled series (vLLM/Prometheus
style): ``counter("ops.flash.calls").inc(bucket="4096x128")`` keeps one
float per distinct label set.  Creation is get-or-create and type-safe
(re-registering ``engine.steps`` as a gauge when it exists as a counter
raises), so hot modules can hold module-level instrument handles.

The zero-overhead-when-disabled contract: telemetry is OFF by default
(module flag, ``ATTN_TPU_OBS=1`` env or :func:`enable` turns it on) and
every mutating method's first statement is the flag check — the
disabled path is one global read and a return, asserted <5% loop
overhead by ``tests/test_obs.py``.  Instrument *creation* is always
allowed (it is cheap, happens at import time, and keeps call sites
branch-free); only recording is gated.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Iterable

from attention_tpu.obs.naming import require_name
from attention_tpu.obs.quantile import DEFAULT_EPS, QuantileDigest, merge_digests

_enabled: bool = os.environ.get("ATTN_TPU_OBS", "") not in ("", "0")


def is_enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Instrument:
    """Shared series bookkeeping for one named instrument family."""

    kind = ""

    def __init__(self, name: str, help: str = ""):
        self.name = require_name(name)
        self.help = help
        self._series: dict[tuple[tuple[str, str], ...], Any] = {}

    def series(self) -> list[dict[str, Any]]:
        return [
            {"name": self.name, "labels": dict(k), "value": v}
            for k, v in sorted(self._series.items())
        ]

    def reset(self) -> None:
        self._series.clear()


class Counter(_Instrument):
    """Monotonic float counter."""

    kind = "counter"

    def inc(self, n: float = 1, **labels: str) -> None:
        if not _enabled:
            return
        if n < 0:
            raise ValueError(f"counter {self.name} cannot go down ({n})")
        key = _label_key(labels)
        self._series[key] = self._series.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Instrument):
    """Last-write-wins value."""

    kind = "gauge"

    def set(self, v: float, **labels: str) -> None:
        if not _enabled:
            return
        self._series[_label_key(labels)] = float(v)

    def value(self, **labels: str) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


#: default histogram buckets (upper bounds) — latency-shaped, unit-free
DEFAULT_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0,
)


class Histogram(_Instrument):
    """Fixed-bucket histogram (cumulative counts + sum, Prometheus
    semantics).  Buckets are frozen at creation — observation is one
    linear scan, no allocation."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs >= 1 bucket")
        self.buckets = bs

    def observe(self, v: float, **labels: str) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = {
                "counts": [0] * (len(self.buckets) + 1),  # +Inf last
                "sum": 0.0,
                "count": 0,
            }
        i = 0
        for b in self.buckets:
            if v <= b:
                break
            i += 1
        s["counts"][i] += 1
        s["sum"] += float(v)
        s["count"] += 1

    def series(self) -> list[dict[str, Any]]:
        return [
            {"name": self.name, "labels": dict(k),
             "buckets": list(self.buckets),
             "counts": list(v["counts"]),
             "sum": v["sum"], "count": v["count"]}
            for k, v in sorted(self._series.items())
        ]


class Digest(_Instrument):
    """Mergeable quantile digest family (one
    :class:`~attention_tpu.obs.quantile.QuantileDigest` per label set).

    The fleet-latency instrument: fixed log-spaced boundaries mean a
    per-replica series merges into a fleet series by bucket-wise
    addition (:meth:`merged`), with relative error bounded by ``eps``.
    Histogram remains the Prometheus-export shape; Digest is the
    quantile source of truth for SLO accounting."""

    kind = "digest"

    def __init__(self, name: str, help: str = "",
                 eps: float = DEFAULT_EPS):
        super().__init__(name, help)
        self.eps = float(eps)

    def observe(self, v: float, **labels: str) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        d = self._series.get(key)
        if d is None:
            d = self._series[key] = QuantileDigest(eps=self.eps)
        d.add(v)

    def digest(self, **labels: str) -> QuantileDigest:
        """The digest for one label set (empty digest if unseen)."""
        d = self._series.get(_label_key(labels))
        return d if d is not None else QuantileDigest(eps=self.eps)

    def merged(self, **labels: str) -> QuantileDigest:
        """Bucket-wise sum of every label set matching the given label
        subset (no labels = the whole family: the fleet rollup)."""
        want = set(_label_key(labels))
        return merge_digests(
            (d for k, d in sorted(self._series.items())
             if want <= set(k)),
            eps=self.eps,
        )

    def series(self) -> list[dict[str, Any]]:
        return [
            {"name": self.name, "labels": dict(k),
             **d.snapshot(), "percentiles": d.percentiles()}
            for k, d in sorted(self._series.items())
        ]


class Registry:
    """Get-or-create home of every instrument family."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Instrument:
        inst = self._instruments.get(name)
        if inst is not None:
            if not isinstance(inst, cls):
                raise TypeError(
                    f"{name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
            return inst
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"{name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def digest(self, name: str, help: str = "",
               eps: float = DEFAULT_EPS) -> Digest:
        return self._get(Digest, name, help, eps=eps)

    def snapshot(self) -> dict[str, Any]:
        """Plain-data view of every series, the exporters' input."""
        out: dict[str, Any] = {"counters": [], "gauges": [],
                               "histograms": [], "digests": []}
        for inst in sorted(self._instruments.values(),
                           key=lambda i: i.name):
            out[inst.kind + "s"].extend(inst.series())
        return out

    def reset(self) -> None:
        """Zero every series (registrations survive — module-level
        handles stay valid)."""
        for inst in self._instruments.values():
            inst.reset()


#: the process-wide default registry — module-level instrument handles
#: throughout the tree hang off this one.
REGISTRY = Registry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


def digest(name: str, help: str = "", eps: float = DEFAULT_EPS) -> Digest:
    return REGISTRY.digest(name, help, eps=eps)
