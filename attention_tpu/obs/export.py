"""Exporters: Prometheus text, JSONL event log, merged Chrome trace.

Three views of the same state:

* :func:`prom_text` — Prometheus text exposition of the registry
  snapshot (scrape-able; round-trip pinned by test);
* :func:`jsonl_lines` / :func:`write_jsonl` — one JSON object per span
  event plus one per metric series, the archival format
  (`profiling.append_jsonl`'s discipline applied to telemetry);
* :func:`chrome_trace` — ONE Chrome-trace/Perfetto JSON timeline
  merging host spans (pid "host") with the device "XLA Modules" lane
  (pid "device") parsed from a ``profiling.trace`` capture by
  `profiling.device_module_slices`.  Host and device clocks have no
  common epoch, so each lane is normalized to its own first event —
  relative alignment within a lane is exact, cross-lane offset is
  nominal (good enough to see an engine step next to its two kernel
  calls; a shared-epoch clock needs device support we don't assume).

:func:`dump` / :func:`load_dump` persist a run's telemetry
(``metrics.json`` + ``events.jsonl`` [+ ``device/`` profiler capture])
so ``cli obs report/export`` can work on finished runs.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator

from attention_tpu.obs import spans
from attention_tpu.obs.naming import prom_name
from attention_tpu.obs.registry import REGISTRY

#: file names inside a dump directory
DUMP_METRICS = "metrics.json"
DUMP_EVENTS = "events.jsonl"
DUMP_DEVICE = "device"


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def prom_text(snapshot: dict[str, Any] | None = None) -> str:
    """Prometheus text exposition (format 0.0.4) of ``snapshot``
    (default: the live registry)."""
    snap = REGISTRY.snapshot() if snapshot is None else snapshot
    lines: list[str] = []
    seen_type: set[str] = set()

    def _type_line(flat: str, kind: str) -> None:
        if flat not in seen_type:
            seen_type.add(flat)
            lines.append(f"# TYPE {flat} {kind}")

    for s in snap.get("counters", []):
        flat = prom_name(s["name"], kind="counter")
        _type_line(flat, "counter")
        lines.append(
            f"{flat}{_fmt_labels(s['labels'])} {_fmt_value(s['value'])}")
    for s in snap.get("gauges", []):
        flat = prom_name(s["name"])
        _type_line(flat, "gauge")
        lines.append(
            f"{flat}{_fmt_labels(s['labels'])} {_fmt_value(s['value'])}")
    for s in snap.get("histograms", []):
        flat = prom_name(s["name"])
        _type_line(flat, "histogram")
        cum = 0
        for b, c in zip(s["buckets"], s["counts"]):
            cum += c
            lab = dict(s["labels"], le=_fmt_value(b))
            lines.append(f"{flat}_bucket{_fmt_labels(lab)} {cum}")
        cum += s["counts"][len(s["buckets"])]
        lab = dict(s["labels"], le="+Inf")
        lines.append(f"{flat}_bucket{_fmt_labels(lab)} {cum}")
        lines.append(
            f"{flat}_sum{_fmt_labels(s['labels'])} {_fmt_value(s['sum'])}")
        lines.append(
            f"{flat}_count{_fmt_labels(s['labels'])} {s['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def jsonl_lines(span_events: list[dict] | None = None,
                snapshot: dict[str, Any] | None = None) -> Iterator[str]:
    """One JSON object per line: span events, then metric series."""
    evs = spans.events() if span_events is None else span_events
    snap = REGISTRY.snapshot() if snapshot is None else snapshot
    for e in evs:
        yield json.dumps({"type": "span", **e})
    for kind in ("counters", "gauges", "histograms"):
        for s in snap.get(kind, []):
            yield json.dumps({"type": kind[:-1], **s})


def write_jsonl(path: str, span_events: list[dict] | None = None,
                snapshot: dict[str, Any] | None = None) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for line in jsonl_lines(span_events, snapshot):
            f.write(line + "\n")


def chrome_trace(span_events: list[dict] | None = None,
                 device_dir: str | None = None) -> dict[str, Any]:
    """The merged host/device timeline as a Chrome-trace dict.

    ``device_dir`` is a ``profiling.trace`` log dir; absent/unparsable
    captures degrade to a host-only timeline (never an error — the CPU
    CI path has no device lane)."""
    evs = spans.events() if span_events is None else span_events
    trace_events: list[dict[str, Any]] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": "host"}},
    ]
    host_t0 = min((e["ts_us"] for e in evs), default=0.0)
    tids = sorted({e["tid"] for e in evs})
    tid_map = {t: i + 1 for i, t in enumerate(tids)}
    for t, i in tid_map.items():
        trace_events.append(
            {"ph": "M", "pid": 1, "tid": i, "name": "thread_name",
             "args": {"name": f"host spans (thread {t})"}})
    for e in evs:
        trace_events.append({
            "ph": "X", "pid": 1, "tid": tid_map[e["tid"]],
            "name": e["name"],
            "ts": round(e["ts_us"] - host_t0, 3),
            "dur": round(e["dur_us"], 3),
        })

    if device_dir is not None:
        from attention_tpu.utils.profiling import device_module_slices

        slices = device_module_slices(device_dir)
        if slices:
            trace_events.append(
                {"ph": "M", "pid": 2, "tid": 0, "name": "process_name",
                 "args": {"name": "device"}})
            trace_events.append(
                {"ph": "M", "pid": 2, "tid": 1, "name": "thread_name",
                 "args": {"name": "XLA Modules"}})
            dev_t0 = min(ts for _, ts, _ in slices)
            for name, ts, dur in slices:
                trace_events.append({
                    "ph": "X", "pid": 2, "tid": 1, "name": name,
                    "ts": round(ts - dev_t0, 3), "dur": round(dur, 3),
                })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def dump(out_dir: str) -> None:
    """Persist the live telemetry state under ``out_dir``."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, DUMP_METRICS), "w") as f:
        json.dump(REGISTRY.snapshot(), f, indent=1)
        f.write("\n")
    write_jsonl(os.path.join(out_dir, DUMP_EVENTS))


def load_dump(run_dir: str) -> tuple[dict[str, Any], list[dict]]:
    """(snapshot, span_events) from a :func:`dump` directory."""
    with open(os.path.join(run_dir, DUMP_METRICS)) as f:
        snapshot = json.load(f)
    evs: list[dict] = []
    events_path = os.path.join(run_dir, DUMP_EVENTS)
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                if row.get("type") == "span":
                    row.pop("type")
                    evs.append(row)
    return snapshot, evs


def device_dir_of(run_dir: str) -> str | None:
    """The dump's device capture dir, if the run profiled one."""
    d = os.path.join(run_dir, DUMP_DEVICE)
    return d if os.path.isdir(d) else None
