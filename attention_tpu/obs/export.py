"""Exporters: Prometheus text, JSONL event log, merged Chrome trace.

Three views of the same state:

* :func:`prom_text` — Prometheus text exposition of the registry
  snapshot (scrape-able; round-trip pinned by test);
* :func:`jsonl_lines` / :func:`write_jsonl` — one JSON object per span
  event plus one per metric series, the archival format
  (`profiling.append_jsonl`'s discipline applied to telemetry);
* :func:`chrome_trace` — ONE Chrome-trace/Perfetto JSON timeline
  merging host spans (pid "host") with the device "XLA Modules" lane
  (pid "device") parsed from a ``profiling.trace`` capture by
  `profiling.device_module_slices`.  Host and device clocks have no
  common epoch, so each lane is normalized to its own first event —
  relative alignment within a lane is exact, cross-lane offset is
  nominal (good enough to see an engine step next to its two kernel
  calls; a shared-epoch clock needs device support we don't assume).

:func:`dump` / :func:`load_dump` persist a run's telemetry
(``metrics.json`` + ``events.jsonl`` [+ ``device/`` profiler capture])
so ``cli obs report/export`` can work on finished runs.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator

from attention_tpu.obs import spans
from attention_tpu.obs import trace as _trace
from attention_tpu.obs.naming import prom_name
from attention_tpu.obs.registry import REGISTRY

#: file names inside a dump directory
DUMP_METRICS = "metrics.json"
DUMP_EVENTS = "events.jsonl"
DUMP_TRACES = "traces.jsonl"
DUMP_SLO = "slo.json"
DUMP_FORECAST = "forecast.json"
DUMP_ANOMALY = "anomaly.json"
DUMP_BLACKBOX = "blackbox.jsonl"
DUMP_DEVICE = "device"

#: percentile-key -> Prometheus quantile-label spelling
_PROM_QUANTILES = {"p50": "0.5", "p90": "0.9", "p99": "0.99",
                   "p999": "0.999"}


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def prom_text(snapshot: dict[str, Any] | None = None) -> str:
    """Prometheus text exposition (format 0.0.4) of ``snapshot``
    (default: the live registry)."""
    snap = REGISTRY.snapshot() if snapshot is None else snapshot
    lines: list[str] = []
    seen_type: set[str] = set()

    def _type_line(flat: str, kind: str) -> None:
        if flat not in seen_type:
            seen_type.add(flat)
            lines.append(f"# TYPE {flat} {kind}")

    for s in snap.get("counters", []):
        flat = prom_name(s["name"], kind="counter")
        _type_line(flat, "counter")
        lines.append(
            f"{flat}{_fmt_labels(s['labels'])} {_fmt_value(s['value'])}")
    for s in snap.get("gauges", []):
        flat = prom_name(s["name"])
        _type_line(flat, "gauge")
        lines.append(
            f"{flat}{_fmt_labels(s['labels'])} {_fmt_value(s['value'])}")
    for s in snap.get("histograms", []):
        flat = prom_name(s["name"])
        _type_line(flat, "histogram")
        cum = 0
        for b, c in zip(s["buckets"], s["counts"]):
            cum += c
            lab = dict(s["labels"], le=_fmt_value(b))
            lines.append(f"{flat}_bucket{_fmt_labels(lab)} {cum}")
        cum += s["counts"][len(s["buckets"])]
        lab = dict(s["labels"], le="+Inf")
        lines.append(f"{flat}_bucket{_fmt_labels(lab)} {cum}")
        lines.append(
            f"{flat}_sum{_fmt_labels(s['labels'])} {_fmt_value(s['sum'])}")
        lines.append(
            f"{flat}_count{_fmt_labels(s['labels'])} {s['count']}")
    for s in snap.get("digests", []):
        # digests export as Prometheus summaries: pre-computed quantile
        # values, not bucket series (Histogram keeps that role)
        flat = prom_name(s["name"])
        _type_line(flat, "summary")
        for pk, q in _PROM_QUANTILES.items():
            lab = dict(s["labels"], quantile=q)
            lines.append(
                f"{flat}{_fmt_labels(lab)} "
                f"{_fmt_value(s['percentiles'][pk])}")
        lines.append(
            f"{flat}_sum{_fmt_labels(s['labels'])} {_fmt_value(s['sum'])}")
        lines.append(
            f"{flat}_count{_fmt_labels(s['labels'])} {s['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def jsonl_lines(span_events: list[dict] | None = None,
                snapshot: dict[str, Any] | None = None) -> Iterator[str]:
    """One JSON object per line: span events, then metric series."""
    evs = spans.events() if span_events is None else span_events
    snap = REGISTRY.snapshot() if snapshot is None else snapshot
    for e in evs:
        yield json.dumps({"type": "span", **e})
    for kind in ("counters", "gauges", "histograms", "digests"):
        for s in snap.get(kind, []):
            yield json.dumps({"type": kind[:-1], **s})


def write_jsonl(path: str, span_events: list[dict] | None = None,
                snapshot: dict[str, Any] | None = None) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for line in jsonl_lines(span_events, snapshot):
            f.write(line + "\n")


#: nominal tick width when laying request journeys on the timeline —
#: ticks are virtual time, so the scale is presentational only
TICK_US = 1000.0


def chrome_trace(span_events: list[dict] | None = None,
                 device_dir: str | None = None,
                 request_traces: dict[str, list[dict]] | None = None,
                 incidents: list[dict[str, Any]] | None = None,
                 ) -> dict[str, Any]:
    """The merged host/device timeline as a Chrome-trace dict.

    ``device_dir`` is a ``profiling.trace`` log dir; absent/unparsable
    captures degrade to a host-only timeline (never an error — the CPU
    CI path has no device lane).  ``request_traces`` (request id ->
    event chain, default the live trace store) adds one lane per
    request under a third process: each journey is a span from submit
    to terminal with an instant mark per trace event.  ``incidents``
    (loaded postmortem bundles) adds a fourth lane marking each
    incident's evidence window and trigger tick."""
    evs = spans.events() if span_events is None else span_events
    trace_events: list[dict[str, Any]] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": "host"}},
    ]
    host_t0 = min((e["ts_us"] for e in evs), default=0.0)
    tids = sorted({e["tid"] for e in evs})
    tid_map = {t: i + 1 for i, t in enumerate(tids)}
    for t, i in tid_map.items():
        trace_events.append(
            {"ph": "M", "pid": 1, "tid": i, "name": "thread_name",
             "args": {"name": f"host spans (thread {t})"}})
    for e in evs:
        trace_events.append({
            "ph": "X", "pid": 1, "tid": tid_map[e["tid"]],
            "name": e["name"],
            "ts": round(e["ts_us"] - host_t0, 3),
            "dur": round(e["dur_us"], 3),
        })

    if device_dir is not None:
        from attention_tpu.utils.profiling import device_module_slices

        slices = device_module_slices(device_dir)
        if slices:
            trace_events.append(
                {"ph": "M", "pid": 2, "tid": 0, "name": "process_name",
                 "args": {"name": "device"}})
            trace_events.append(
                {"ph": "M", "pid": 2, "tid": 1, "name": "thread_name",
                 "args": {"name": "XLA Modules"}})
            dev_t0 = min(ts for _, ts, _ in slices)
            for name, ts, dur in slices:
                trace_events.append({
                    "ph": "X", "pid": 2, "tid": 1, "name": name,
                    "ts": round(ts - dev_t0, 3), "dur": round(dur, 3),
                })

    chains = (_trace.all_traces() if request_traces is None
              else request_traces)
    if chains:
        trace_events.append(
            {"ph": "M", "pid": 3, "tid": 0, "name": "process_name",
             "args": {"name": "requests"}})
        for lane, rid in enumerate(sorted(chains), start=1):
            chain = chains[rid]
            if not chain:
                continue
            trace_events.append(
                {"ph": "M", "pid": 3, "tid": lane, "name": "thread_name",
                 "args": {"name": rid}})
            t_first = min(ev["tick"] for ev in chain)
            t_last = max(ev["tick"] for ev in chain)
            trace_events.append({
                "ph": "X", "pid": 3, "tid": lane, "name": rid,
                "ts": t_first * TICK_US,
                "dur": max((t_last - t_first) * TICK_US, 1.0),
                "args": {"events": len(chain),
                         "terminal": _trace.terminal_of(chain)},
            })
            for ev in chain:
                args = {k: v for k, v in ev.items()
                        if k != "event" and v is not None}
                trace_events.append({
                    "ph": "i", "pid": 3, "tid": lane, "s": "t",
                    "name": ev["event"], "ts": ev["tick"] * TICK_US,
                    "args": args,
                })

    if incidents:
        from attention_tpu.obs.postmortem import incident_lane

        trace_events.extend(incident_lane(incidents))
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def dump(out_dir: str) -> None:
    """Persist the live telemetry state under ``out_dir``."""
    from attention_tpu.obs import blackbox as _blackbox

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, DUMP_METRICS), "w") as f:
        json.dump(REGISTRY.snapshot(), f, indent=1)
        f.write("\n")
    write_jsonl(os.path.join(out_dir, DUMP_EVENTS))
    chains = _trace.all_traces()
    if chains:
        with open(os.path.join(out_dir, DUMP_TRACES), "w") as f:
            for rid in sorted(chains):
                f.write(json.dumps(
                    {"request_id": rid, "events": chains[rid]}) + "\n")
    ring = _blackbox.events()
    if ring:
        with open(os.path.join(out_dir, DUMP_BLACKBOX), "w") as f:
            for rec in ring:
                f.write(json.dumps(rec, sort_keys=True) + "\n")


def load_dump(run_dir: str) -> tuple[dict[str, Any], list[dict]]:
    """(snapshot, span_events) from a :func:`dump` directory."""
    with open(os.path.join(run_dir, DUMP_METRICS)) as f:
        snapshot = json.load(f)
    evs: list[dict] = []
    events_path = os.path.join(run_dir, DUMP_EVENTS)
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                if row.get("type") == "span":
                    row.pop("type")
                    evs.append(row)
    return snapshot, evs


def load_traces(run_dir: str) -> dict[str, list[dict]]:
    """Request-trace chains from a :func:`dump` directory (request id
    -> event chain; {} when the run recorded none)."""
    path = os.path.join(run_dir, DUMP_TRACES)
    chains: dict[str, list[dict]] = {}
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                chains[row["request_id"]] = row["events"]
    return chains


def write_slo(out_dir: str, report: dict[str, Any]) -> None:
    """Persist an `obs.slo.slo_report` next to the metrics dump, in
    canonical form (sorted keys) so same-seed runs are byte-identical."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, DUMP_SLO), "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")


def load_slo(run_dir: str) -> dict[str, Any] | None:
    """The dump's SLO report, or None if the run wrote none."""
    path = os.path.join(run_dir, DUMP_SLO)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_forecast(out_dir: str, report: dict[str, Any]) -> None:
    """Persist an `obs.capacity.observatory_report` next to the metrics
    dump, in canonical form (sorted keys) so same-seed runs are
    byte-identical."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, DUMP_FORECAST), "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")


def load_forecast(run_dir: str) -> dict[str, Any] | None:
    """The dump's forecast report, or None if the run wrote none."""
    path = os.path.join(run_dir, DUMP_FORECAST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_anomaly(out_dir: str, report: dict[str, Any]) -> None:
    """Persist an `obs.anomaly.AnomalyTracker.report` next to the
    metrics dump, in canonical form (sorted keys) so same-seed runs
    are byte-identical."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, DUMP_ANOMALY), "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")


def load_anomaly(run_dir: str) -> dict[str, Any] | None:
    """The dump's anomaly report, or None if the run wrote none."""
    path = os.path.join(run_dir, DUMP_ANOMALY)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_blackbox(run_dir: str) -> list[dict[str, Any]]:
    """Flight-recorder ring records from a :func:`dump` directory
    ([] when the run recorded none)."""
    path = os.path.join(run_dir, DUMP_BLACKBOX)
    out: list[dict[str, Any]] = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


def device_dir_of(run_dir: str) -> str | None:
    """The dump's device capture dir, if the run profiled one."""
    d = os.path.join(run_dir, DUMP_DEVICE)
    return d if os.path.isdir(d) else None
