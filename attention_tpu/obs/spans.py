"""Host-side spans: named start/duration events in a bounded ring.

``span(name)`` is the host half of the merged timeline: it records a
(name, ts, dur, thread) event into an in-memory ring buffer (bounded —
a long serve run cannot grow without bound) and, while enabled, also
enters ``profiling.annotate(name)`` so the SAME name shows up in HLO op
names and on the XLA profiler timeline.  The chrome exporter
(`obs.export.chrome_trace`) lays these events alongside the device
lane parsed from a `profiling.trace` capture.

Disabled path: ``span()`` returns one shared no-op context manager —
a global read, an attribute load, and two empty method calls; no
allocation, no clock read (the <5%-overhead contract,
``tests/test_obs.py::test_disabled_overhead_under_5_percent``).
"""

from __future__ import annotations

import threading
import time

from attention_tpu.obs import registry as _registry
from attention_tpu.obs.naming import require_name

#: ring capacity (events); oldest events drop first
SPAN_RING_CAPACITY = 65536

_lock = threading.Lock()
_ring: list[tuple[str, float, float, int]] = []  # (name, ts_us, dur_us, tid)
_ring_start = 0  # index of the logical head when the ring has wrapped
_t0 = time.perf_counter()


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "_t_start", "_scope")

    def __init__(self, name: str):
        self.name = name
        self._scope = None

    def __enter__(self):
        # compose with the device-side annotation so host span and HLO
        # region share one name; annotate is jax.named_scope, legal
        # inside and outside traces
        from attention_tpu.utils.profiling import annotate

        self._scope = annotate(self.name)
        self._scope.__enter__()
        self._t_start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_us = (time.perf_counter() - self._t_start) * 1e6
        scope, self._scope = self._scope, None
        if scope is not None:
            scope.__exit__(*exc)
        record_event(self.name, (self._t_start - _t0) * 1e6, dur_us)
        return False


def span(name: str):
    """Context manager timing the enclosed block under ``name``.

    When telemetry is disabled this is a shared no-op; the name is NOT
    validated on the fast path (the lint script and the enabled path
    cover it)."""
    if not _registry._enabled:
        return _NOOP
    require_name(name)
    return _Span(name)


def record_event(name: str, ts_us: float, dur_us: float,
                 tid: int | None = None) -> None:
    """Append one span event to the ring (used by `_Span` and by code
    that measured a region manually)."""
    if not _registry._enabled:
        return
    if tid is None:
        tid = threading.get_ident()
    with _lock:
        global _ring_start
        if len(_ring) < SPAN_RING_CAPACITY:
            _ring.append((name, ts_us, dur_us, tid))
        else:
            _ring[_ring_start] = (name, ts_us, dur_us, tid)
            _ring_start = (_ring_start + 1) % SPAN_RING_CAPACITY


def events() -> list[dict[str, float | str | int]]:
    """Recorded span events, oldest first, as plain dicts."""
    with _lock:
        ordered = _ring[_ring_start:] + _ring[:_ring_start]
    return [
        {"name": n, "ts_us": round(ts, 3), "dur_us": round(dur, 3),
         "tid": tid}
        for n, ts, dur, tid in ordered
    ]


def clear() -> None:
    global _ring, _ring_start
    with _lock:
        _ring = []
        _ring_start = 0
