"""Fleet flight recorder: a bounded ring of typed causal events.

Where a :mod:`~attention_tpu.obs.trace` chain is the journey of ONE
request, the **blackbox ring** is the fleet's own black box: every
causal decision the serving stack makes — routing choices, watermark
sheds, prefill-lease grants/expiries, prefix-store imports/evictions/
corruptions, replica kills/restarts/migrations, chaos fault
injections, anomaly-detector firings — lands in one append-only
bounded ring, each record stamped with the four deterministic
coordinates of the serving stack —

    ``(front-end tick, replica id, incarnation, engine step)``

— never wall time, so the same seed produces a byte-identical ring.
Event kinds are the closed enum ``obs/naming.py:BLACKBOX_EVENTS``
(rejected at note time, linted as ATP507 at review time).  When an
incident fires, :mod:`~attention_tpu.obs.postmortem` slices this ring
around the incident tick: the ring is the causal evidence the
postmortem timeline is reconstructed from.

Gating: recording is off unless telemetry is enabled (the PR 3
zero-overhead contract — the disabled path is one global read and a
return) or a :func:`capture` scope is active.  ``capture`` exists for
the chaos harness: fault campaigns assert incident completeness
without turning the whole registry on.
"""

from __future__ import annotations

import collections
import contextlib
import threading
from typing import Any, Iterator

from attention_tpu.obs import registry as _registry
from attention_tpu.obs.naming import require_blackbox_event

#: most events kept live; the oldest record drops first
BLACKBOX_CAPACITY = 65536

_lock = threading.Lock()
_ring: collections.deque[dict[str, Any]] = collections.deque(
    maxlen=BLACKBOX_CAPACITY)
_seq = 0  # total records ever noted (monotone across evictions)
_forced = 0  # >0 inside a capture() scope: record regardless of obs flag


def active() -> bool:
    """True iff flight recording is currently on."""
    return _registry._enabled or _forced > 0


@contextlib.contextmanager
def capture() -> Iterator[None]:
    """Scope that records flight events even while telemetry is
    disabled.

    Clears the ring on entry — each chaos plan gets an isolated ring
    to assert incident completeness over (synthetic fault schedules
    repeat across plans)."""
    global _forced, _seq
    with _lock:
        _forced += 1
        _ring.clear()
        _seq = 0
    try:
        yield
    finally:
        with _lock:
            _forced -= 1


def note(kind: str, *, tick: int, replica: str | None = None,
         incarnation: int = 0, step: int = -1, **extra: Any) -> None:
    """Append one typed event to the fleet ring.

    ``extra`` carries decision details (``reason`` for routing,
    ``key`` for store events, ``fault`` for injections) and must be
    plain scalars — the ring is serialized verbatim into incident
    bundles."""
    global _seq
    if not (_registry._enabled or _forced):
        return
    require_blackbox_event(kind)
    rec: dict[str, Any] = {
        "kind": kind,
        "tick": int(tick),
        "replica": replica,
        "incarnation": int(incarnation),
        "step": int(step),
    }
    for k in sorted(extra):
        v = extra[k]
        if v is not None and not isinstance(v, (str, int, float, bool)):
            raise TypeError(
                f"blackbox extra {k}={v!r} must be a plain scalar"
            )
        rec[k] = v
    with _lock:
        rec["seq"] = _seq
        _seq += 1
        _ring.append(rec)


def events(*, since_tick: int | None = None,
           until_tick: int | None = None,
           kind: str | None = None) -> list[dict[str, Any]]:
    """Ring records oldest first (copies), optionally filtered to a
    tick window ``[since_tick, until_tick]`` and/or one event kind —
    the postmortem bundle's ring-slice query."""
    with _lock:
        recs = [dict(r) for r in _ring]
    if since_tick is not None:
        recs = [r for r in recs if r["tick"] >= since_tick]
    if until_tick is not None:
        recs = [r for r in recs if r["tick"] <= until_tick]
    if kind is not None:
        recs = [r for r in recs if r["kind"] == kind]
    return recs


def depth() -> int:
    """Records currently held in the ring."""
    with _lock:
        return len(_ring)


def total() -> int:
    """Records ever noted since the last clear (>= :func:`depth` once
    the ring has evicted)."""
    with _lock:
        return _seq


def clear() -> None:
    global _seq
    with _lock:
        _ring.clear()
        _seq = 0
