"""Capacity accounting on top of the forecaster: the observatory.

The ROADMAP's elastic-autoscaling item needs four numbers before any
scaling decision is measurable — this module computes all of them from
plain deterministic inputs (no registry reads; telemetry stays
optional):

* **per-replica effective tokens/tick** — tokens each replica actually
  emitted over the run, normalized by virtual ticks;
* **fleet headroom** — ``1 - last observed mean pressure``, the
  fraction of fleet capacity still unspent;
* **cost-per-token** — replica-ticks burned per emitted token
  (``alive_replicas * ticks / tokens``): the unit a scale-in decision
  minimizes;
* **time-to-saturation** — the first *predicted* tick at which the
  pressure forecast crosses the shed / downclass watermarks, straight
  off the :mod:`attention_tpu.obs.forecast` horizon table.

:func:`observatory_report` assembles the combined ``forecast.json``
document (forecast blocks + capacity block + the raw samples), and
:func:`rebuild_report` recomputes it byte-identically from a loaded
dump — the contract behind ``cli obs forecast --run DIR [--horizon H]``.
"""

from __future__ import annotations

from typing import Any

from attention_tpu.obs import forecast as _forecast
from attention_tpu.obs import registry as _registry
from attention_tpu.obs.forecast import ForecastPolicy, _r6
from attention_tpu.obs.naming import (
    SERIES_CAPACITY_HEADROOM,
    SERIES_COST_PER_TOKEN,
)

#: default watermarks, mirrored from frontend.degrade.ShedPolicy
DEFAULT_SHED_PRESSURE = 0.92
DEFAULT_DOWNCLASS_PRESSURE = 0.75


def capacity_report(inputs: dict[str, Any],
                    pressure_block: dict[str, Any] | None = None, *,
                    shed_pressure: float = DEFAULT_SHED_PRESSURE,
                    downclass_pressure: float = DEFAULT_DOWNCLASS_PRESSURE,
                    ) -> dict[str, Any]:
    """Deterministic capacity block.

    ``inputs``: ``{"ticks": int, "alive": int, "last_pressure": float,
    "replica_tokens": {replica_id_str: tokens}}`` — replica ids are
    strings so the block round-trips through JSON byte-identically.
    """
    ticks = int(inputs.get("ticks", 0))
    alive = int(inputs.get("alive", 0))
    last_pressure = float(inputs.get("last_pressure", 0.0))
    per = inputs.get("replica_tokens", {}) or {}
    rows = []
    total = 0
    for rid in sorted(per):
        tok = int(per[rid])
        total += tok
        rows.append({
            "replica": str(rid),
            "tokens": tok,
            "tokens_per_tick": _r6(tok / ticks) if ticks else 0.0,
        })
    headroom = min(1.0, max(0.0, 1.0 - last_pressure))
    cost = _r6(alive * ticks / total) if total else None
    saturation = {}
    for name, wm in (("downclass", downclass_pressure),
                     ("shed", shed_pressure)):
        row = (_forecast.crossing(pressure_block, wm)
               if pressure_block is not None else None)
        saturation[name] = {
            "watermark": _r6(wm),
            "h": row["h"] if row else None,
            "tick": row["tick"] if row else None,
            "pressure": row["mean"] if row else None,
        }
    return {
        "replicas": rows,
        "fleet": {
            "ticks": ticks,
            "alive_replicas": alive,
            "tokens": total,
            "tokens_per_tick": _r6(total / ticks) if ticks else 0.0,
            "headroom": _r6(headroom),
            "cost_per_token": cost,
        },
        "time_to_saturation": saturation,
    }


def observatory_report(samples: dict[str, Any],
                       capacity_inputs: dict[str, Any], *,
                       policy: ForecastPolicy | None = None,
                       horizon: int | None = None,
                       shed_pressure: float = DEFAULT_SHED_PRESSURE,
                       downclass_pressure: float = DEFAULT_DOWNCLASS_PRESSURE,
                       ) -> dict[str, Any]:
    """The full forecast+capacity document serve-sim dumps as
    ``forecast.json``.  Carries the raw samples so the report can be
    rebuilt (at any horizon) from the dump alone."""
    p = policy or ForecastPolicy()
    doc = _forecast.forecast_report(samples, policy=p, horizon=horizon)
    pblock = next((b for b in doc["series"]
                   if b["name"] == _forecast.PRESSURE_SERIES), None)
    doc["watermarks"] = {"shed": _r6(shed_pressure),
                         "downclass": _r6(downclass_pressure)}
    doc["capacity"] = capacity_report(
        capacity_inputs, pblock,
        shed_pressure=shed_pressure,
        downclass_pressure=downclass_pressure)
    doc["samples"] = {name: [float(v) for v in samples[name]]
                      for name in sorted(samples)}
    doc["capacity_inputs"] = {
        "ticks": int(capacity_inputs.get("ticks", 0)),
        "alive": int(capacity_inputs.get("alive", 0)),
        "last_pressure": float(capacity_inputs.get("last_pressure", 0.0)),
        "replica_tokens": {
            str(k): int(v)
            for k, v in sorted(
                (capacity_inputs.get("replica_tokens", {}) or {}).items())
        },
    }
    return doc


def rebuild_report(doc: dict[str, Any], *,
                   horizon: int | None = None) -> dict[str, Any]:
    """Recompute an observatory report from its own embedded samples.

    With ``horizon=None`` the rebuild is byte-identical to ``doc``
    (pinned by test); a different horizon re-runs the same state over
    a longer/shorter table."""
    p = ForecastPolicy.from_dict(doc["policy"])
    h = int(doc["horizon"] if horizon is None else horizon)
    return observatory_report(
        doc["samples"], doc["capacity_inputs"], policy=p, horizon=h,
        shed_pressure=float(doc["watermarks"]["shed"]),
        downclass_pressure=float(doc["watermarks"]["downclass"]))


def publish(report: dict[str, Any]) -> None:
    """Mirror the capacity headline gauges onto the frozen registry
    series (no-op while telemetry is disabled)."""
    if not _registry.is_enabled():
        return
    cap = report.get("capacity", report)
    fleet = cap["fleet"]
    head = _registry.gauge(SERIES_CAPACITY_HEADROOM,
                           "fleet capacity headroom (1 = idle)")
    head.set(fleet["headroom"])
    if fleet["cost_per_token"] is not None:
        cost = _registry.gauge(SERIES_COST_PER_TOKEN,
                               "replica-ticks per emitted token")
        cost.set(fleet["cost_per_token"])
