"""Incident postmortems: atomic evidence bundles + causal timelines.

When something goes wrong — a typed error, a chaos invariant
violation, an anomaly-detector firing, an injected fault — the
serving stack dumps an ``incident-<tick>/`` bundle: the flight-
recorder ring sliced around the incident tick, the registry snapshot,
and every request trace chain active in the window.  The bundle is
the whole story: ``cli obs postmortem --run DIR`` reconstructs the
cross-replica causal timeline from the bundle alone, correlates the
alarm with its trigger events, and renders a byte-deterministic
incident report (same seed → same bytes, the `write_slo` canon).

Bundles are written with the snapshot discipline: every file is
fsync'd inside a temp directory, then one ``os.replace`` publishes
the bundle — a crash mid-dump leaves either no bundle or a whole one,
never a torn one.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any

from attention_tpu.obs import blackbox as _blackbox
from attention_tpu.obs import trace as _trace
from attention_tpu.obs.registry import REGISTRY

INCIDENT_REPORT_VERSION = 1

#: bundle directory prefix (``incident-<tick>[-<n>]``)
INCIDENT_PREFIX = "incident-"

#: bundle member files
INCIDENT_META = "incident.json"
INCIDENT_RING = "blackbox.jsonl"
INCIDENT_METRICS = "metrics.json"
INCIDENT_TRACES = "traces.jsonl"

#: ring/trace slice width: ticks of history captured before the
#: incident tick
INCIDENT_WINDOW = 64

#: the closed set of incident causes — `incident.json:cause` is one of
#: these, and the chaos `incident_completeness` invariant reasons about
#: them structurally
INCIDENT_CAUSES = frozenset({
    "fault",        # a chaos injector fired (detail: fault kind)
    "typed_error",  # a fault-class typed error surfaced in the frontend
    "detector",     # an obs/anomaly.py detector crossed its bound
    "invariant",    # a chaos invariant checker reported violations
    "actuation",    # a fleet scale-down was followed by sheds inside
                    # its guard window (mis-actuation)
})


def _fsync_write(path: str, text: str) -> None:
    with open(path, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())


def _jsonl(rows: list[dict[str, Any]]) -> str:
    return "".join(json.dumps(r, sort_keys=True) + "\n" for r in rows)


def _deterministic_snapshot() -> dict[str, Any]:
    """Registry snapshot minus the wall-clock reporting channels.

    ``*_ms`` instruments (``engine.step.wall_ms``,
    ``engine.snapshot.save_ms``, ``engine.step.collective_ms``) time
    host/device walls — ATP801's sanctioned reporting channel,
    excluded from every byte-determinism contract in the repo.  An
    incident bundle IS such a contract (same seed must dump
    byte-identical bundles), so they stay out of ``metrics.json``."""
    return {
        kind: [s for s in series if not s["name"].endswith("_ms")]
        if isinstance(series, list) else series
        for kind, series in REGISTRY.snapshot().items()
    }


def dump_incident(out_dir: str, *, tick: int, cause: str,
                  detail: dict[str, Any],
                  window: int = INCIDENT_WINDOW,
                  name: str | None = None) -> str:
    """Atomically write one ``incident-<tick>/`` bundle under
    ``out_dir``; returns the published bundle path.

    The bundle captures the live stores at dump time: the blackbox
    ring sliced to ``[tick - window, tick]``, the registry snapshot
    (minus wall-clock channels — see ``_deterministic_snapshot``),
    and every trace chain with an event in the window.  ``detail``
    must be plain scalars (it is the incident's identity — the
    completeness invariant matches bundles to causes by it)."""
    if cause not in INCIDENT_CAUSES:
        raise ValueError(
            f"unknown incident cause {cause!r}; causes are the closed "
            f"set: {', '.join(sorted(INCIDENT_CAUSES))}")
    for k, v in detail.items():
        if v is not None and not isinstance(v, (str, int, float, bool)):
            raise TypeError(
                f"incident detail {k}={v!r} must be a plain scalar")
    os.makedirs(out_dir, exist_ok=True)
    if name is None:
        name = f"{INCIDENT_PREFIX}{int(tick):06d}"
        final = os.path.join(out_dir, name)
        n = 2
        while os.path.exists(final):
            final = os.path.join(out_dir, f"{name}-{n}")
            n += 1
    else:
        final = os.path.join(out_dir, name)

    lo = int(tick) - int(window)
    ring = _blackbox.events(since_tick=lo, until_tick=int(tick))
    chains = {
        rid: chain
        for rid, chain in sorted(_trace.all_traces().items())
        if any(lo <= ev["tick"] <= int(tick) for ev in chain)
    }
    meta = {
        "version": INCIDENT_REPORT_VERSION,
        "generated_at": 0,
        "tick": int(tick),
        "cause": cause,
        "detail": {k: detail[k] for k in sorted(detail)},
        "window": int(window),
        "ring_events": len(ring),
        "trace_chains": len(chains),
    }

    tmp = tempfile.mkdtemp(dir=out_dir, prefix=".tmp-incident-")
    try:
        _fsync_write(os.path.join(tmp, INCIDENT_META),
                     json.dumps(meta, indent=1, sort_keys=True) + "\n")
        _fsync_write(os.path.join(tmp, INCIDENT_RING), _jsonl(ring))
        _fsync_write(
            os.path.join(tmp, INCIDENT_METRICS),
            json.dumps(_deterministic_snapshot(), indent=1,
                       sort_keys=True) + "\n")
        _fsync_write(
            os.path.join(tmp, INCIDENT_TRACES),
            _jsonl([{"request_id": rid, "events": chains[rid]}
                    for rid in sorted(chains)]))
        dfd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        os.replace(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


class PostmortemWriter:
    """Per-frontend incident dumper: dedup + flood control.

    One writer owns one run's incident directory.  ``maybe_dump``
    writes at most one bundle per distinct ``(tick, cause, detail)``
    (an injector marking the same fault twice, or a detector whose
    condition is re-reported, folds into one incident) and stops at
    ``limit`` bundles — a chaotic campaign must not turn the disk into
    the incident."""

    def __init__(self, out_dir: str, *, window: int = INCIDENT_WINDOW,
                 limit: int = 256):
        self.out_dir = out_dir
        self.window = int(window)
        self.limit = int(limit)
        #: (tick, cause, sorted detail items) of every bundle written
        self.written: list[tuple[int, str, tuple]] = []
        self.suppressed = 0

    def maybe_dump(self, *, tick: int, cause: str,
                   detail: dict[str, Any]) -> str | None:
        key = (int(tick), cause,
               tuple(sorted((k, v) for k, v in detail.items())))
        if key in self._seen():
            return None
        if len(self.written) >= self.limit:
            self.suppressed += 1
            return None
        path = dump_incident(self.out_dir, tick=tick, cause=cause,
                             detail=detail, window=self.window)
        self.written.append(key)
        _blackbox.note("incident_dump", tick=int(tick), cause=cause,
                       bundle=os.path.basename(path))
        return path

    def _seen(self) -> set[tuple]:
        return set(self.written)


# -- bundle loading + timeline reconstruction ------------------------------


def list_incidents(run_dir: str) -> list[str]:
    """Bundle directories under ``run_dir``, incident order (tick,
    then collision suffix)."""
    if not os.path.isdir(run_dir):
        return []
    out = []
    for entry in sorted(os.listdir(run_dir)):
        full = os.path.join(run_dir, entry)
        if (entry.startswith(INCIDENT_PREFIX) and os.path.isdir(full)
                and os.path.isfile(os.path.join(full, INCIDENT_META))):
            out.append(full)
    return out


def load_incident(bundle_dir: str) -> dict[str, Any]:
    """One bundle, parsed: ``{"name", "meta", "ring", "traces",
    "snapshot"}`` — everything the timeline needs, from disk alone."""
    with open(os.path.join(bundle_dir, INCIDENT_META)) as f:
        meta = json.load(f)
    ring: list[dict[str, Any]] = []
    ring_path = os.path.join(bundle_dir, INCIDENT_RING)
    if os.path.exists(ring_path):
        with open(ring_path) as f:
            ring = [json.loads(line) for line in f if line.strip()]
    traces: dict[str, list[dict[str, Any]]] = {}
    traces_path = os.path.join(bundle_dir, INCIDENT_TRACES)
    if os.path.exists(traces_path):
        with open(traces_path) as f:
            for line in f:
                if line.strip():
                    row = json.loads(line)
                    traces[row["request_id"]] = row["events"]
    snapshot: dict[str, Any] = {}
    metrics_path = os.path.join(bundle_dir, INCIDENT_METRICS)
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            snapshot = json.load(f)
    return {"name": os.path.basename(bundle_dir), "meta": meta,
            "ring": ring, "traces": traces, "snapshot": snapshot}


_COORD_KEYS = ("kind", "event", "tick", "replica", "incarnation",
               "step", "seq", "request_id")


def _fmt_entry(tick: int, label: str, replica: str | None,
               incarnation: int, step: int,
               extras: dict[str, Any]) -> str:
    where = ""
    if replica is not None:
        where = f" replica={replica} inc={incarnation}"
        if step >= 0:
            where += f" step={step}"
    tail_items = [f"{k}={extras[k]}" for k in sorted(extras)
                  if extras[k] is not None]
    tail = (" [" + " ".join(tail_items) + "]") if tail_items else ""
    return f"  [tick {tick:>5}] {label}{where}{tail}"


def timeline(bundle: dict[str, Any]) -> list[str]:
    """The cross-replica causal timeline of one loaded bundle: ring
    records and trace-chain events merged in (tick, source, seq)
    order, one line each."""
    entries: list[tuple[tuple, str]] = []
    for rec in bundle["ring"]:
        extras = {k: v for k, v in rec.items() if k not in _COORD_KEYS}
        line = _fmt_entry(rec["tick"], rec["kind"], rec.get("replica"),
                          rec.get("incarnation", 0),
                          rec.get("step", -1), extras)
        entries.append(((rec["tick"], 0, rec.get("seq", 0), ""), line))
    for rid in sorted(bundle["traces"]):
        for i, ev in enumerate(bundle["traces"][rid]):
            extras = {k: v for k, v in ev.items()
                      if k not in _COORD_KEYS}
            extras["request"] = rid
            line = _fmt_entry(ev["tick"], f"trace:{ev['event']}",
                              ev.get("replica"),
                              ev.get("incarnation", 0),
                              ev.get("step", -1), extras)
            entries.append(((ev["tick"], 1, i, rid), line))
    entries.sort(key=lambda e: e[0])
    return [line for _, line in entries]


#: ring kinds that can be an incident's trigger, by cause
_TRIGGER_KINDS = {
    "fault": ("fault_injected",),
    "detector": ("anomaly_fire",),
    "typed_error": ("shed", "replica_kill", "store_corrupt",
                    "lease_expire"),
    "invariant": ("fault_injected", "anomaly_fire"),
    "actuation": ("scale_down", "shed"),
}


def correlate(bundle: dict[str, Any]) -> list[str]:
    """Alarm → trigger correlation: the ring records that plausibly
    caused this incident (matching kind, at or before the incident
    tick, nearest first)."""
    meta = bundle["meta"]
    kinds = _TRIGGER_KINDS.get(meta["cause"], ())
    cands = [rec for rec in bundle["ring"]
             if rec["kind"] in kinds and rec["tick"] <= meta["tick"]]
    cands.sort(key=lambda r: (-r["tick"], -r.get("seq", 0)))
    lines = []
    for rec in cands[:8]:
        extras = {k: v for k, v in rec.items() if k not in _COORD_KEYS}
        lines.append(_fmt_entry(rec["tick"], rec["kind"],
                                rec.get("replica"),
                                rec.get("incarnation", 0),
                                rec.get("step", -1), extras))
    return lines


def report_lines(run_dir: str) -> list[str]:
    """The full ``cli obs postmortem`` body for every bundle under
    ``run_dir`` — byte-deterministic (sorted bundles, sorted keys, no
    clocks)."""
    bundles = [load_incident(d) for d in list_incidents(run_dir)]
    lines = [f"incident postmortem: {len(bundles)} bundle(s)"]
    for b in bundles:
        meta = b["meta"]
        detail = " ".join(f"{k}={meta['detail'][k]}"
                          for k in sorted(meta["detail"]))
        lines.append("")
        lines.append(f"== {b['name']} ==")
        lines.append(f"cause: {meta['cause']}"
                     + (f" [{detail}]" if detail else ""))
        lines.append(
            f"window: ticks {meta['tick'] - meta['window']}.."
            f"{meta['tick']}, {meta['ring_events']} ring event(s), "
            f"{meta['trace_chains']} trace chain(s)")
        corr = correlate(b)
        if corr:
            lines.append("trigger correlation:")
            lines.extend(corr)
        lines.append("timeline:")
        lines.extend(timeline(b))
    return lines


def incident_lane(bundles: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Chrome-trace events for the incident lane (pid 4): one span
    per bundle covering its evidence window plus one instant at the
    incident tick — rendered beside the host/device/request lanes by
    `obs.export.chrome_trace`."""
    from attention_tpu.obs.export import TICK_US

    if not bundles:
        return []
    out: list[dict[str, Any]] = [
        {"ph": "M", "pid": 4, "tid": 0, "name": "process_name",
         "args": {"name": "incidents"}},
        {"ph": "M", "pid": 4, "tid": 1, "name": "thread_name",
         "args": {"name": "incident bundles"}},
    ]
    for b in bundles:
        meta = b["meta"]
        t0 = (meta["tick"] - meta["window"]) * TICK_US
        out.append({
            "ph": "X", "pid": 4, "tid": 1, "name": b["name"],
            "ts": t0,
            "dur": max(meta["window"] * TICK_US, 1.0),
            "args": {"cause": meta["cause"], **meta["detail"]},
        })
        out.append({
            "ph": "i", "pid": 4, "tid": 1, "s": "t",
            "name": meta["cause"], "ts": meta["tick"] * TICK_US,
            "args": {"bundle": b["name"]},
        })
    return out
