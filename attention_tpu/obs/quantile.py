"""Deterministic mergeable quantile digest over log-spaced buckets.

The registry's fixed-bucket :class:`~attention_tpu.obs.registry.Histogram`
is the Prometheus-facing view; it approximates tail quantiles only as
well as its hand-picked bucket edges.  This module is the fleet-level
latency instrument: a DDSketch-style digest whose bucket boundaries are
FIXED powers of ``gamma = (1+eps)/(1-eps)`` — the same boundaries in
every process — so

* **merge is bucket-wise addition** (replica digests sum into a fleet
  digest with zero coordination, no resampling, no approximation on
  top of approximation; pinned exact by test), and
* **relative error is bounded**: any value in bucket ``i`` lies in
  ``(gamma^(i-1), gamma^i]`` and is reported as the geometric midpoint,
  so ``|est - true| / true <= eps`` for every quantile, point mass to
  heavy tail alike.

Everything is plain Python floats/ints and insertion-order-free
(buckets keyed by integer index, emitted sorted), so a digest snapshot
is byte-deterministic for a deterministic stream of observations —
the property `slo_report()` builds on.
"""

from __future__ import annotations

import math
from typing import Any, Iterable

#: default relative-error bound (1%): p99 of a 1000-tick TTFT tail is
#: reported within 10 ticks of truth
DEFAULT_EPS = 0.01

#: the quantiles every report surfaces
REPORT_QUANTILES = (0.5, 0.9, 0.99, 0.999)


def _q_label(q: float) -> str:
    """``0.5 -> "p50"``, ``0.999 -> "p999"`` — the frozen report
    spelling."""
    return "p" + f"{q}".split(".")[1].ljust(2, "0")


class QuantileDigest:
    """Mergeable quantile digest with bounded relative error.

    ``min_value`` floors the resolvable magnitude: observations in
    ``[0, min_value]`` share the exact "zero" bucket (latencies of 0
    ticks are common and must not hit ``log``).  Negative observations
    are a caller bug and raise.
    """

    __slots__ = ("eps", "min_value", "_gamma", "_log_gamma",
                 "zero", "buckets", "count", "sum", "min", "max")

    def __init__(self, eps: float = DEFAULT_EPS,
                 min_value: float = 1e-9):
        if not 0.0 < eps < 1.0:
            raise ValueError(f"eps must be in (0, 1), got {eps}")
        if min_value <= 0.0:
            raise ValueError(f"min_value must be > 0, got {min_value}")
        self.eps = float(eps)
        self.min_value = float(min_value)
        self._gamma = (1.0 + self.eps) / (1.0 - self.eps)
        self._log_gamma = math.log(self._gamma)
        self.zero = 0
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- recording --------------------------------------------------------

    def _index(self, v: float) -> int:
        return math.ceil(math.log(v) / self._log_gamma)

    def add(self, v: float, n: int = 1) -> None:
        v = float(v)
        if v < 0.0:
            raise ValueError(f"digest values must be >= 0, got {v}")
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if v <= self.min_value:
            self.zero += n
        else:
            i = self._index(v)
            self.buckets[i] = self.buckets.get(i, 0) + n
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def extend(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    # -- querying ---------------------------------------------------------

    def _value_of(self, index: int) -> float:
        # geometric midpoint of (gamma^(i-1), gamma^i]: the estimate
        # whose worst-case relative error is exactly eps
        return 2.0 * self._gamma ** index / (self._gamma + 1.0)

    def quantile(self, q: float) -> float:
        """The ``q``-quantile estimate (0 for an empty digest)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return 0.0
        # nearest-rank on the bucketed CDF; min/max are exact so the
        # extreme quantiles never overshoot the observed range
        rank = q * (self.count - 1)
        seen = self.zero
        if rank < seen:
            return self.min if self.min < math.inf else 0.0
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if rank < seen:
                est = self._value_of(i)
                return min(max(est, self.min), self.max)
        return self.max

    def percentiles(self) -> dict[str, float]:
        """The frozen report quantiles: ``{"p50": ..., ..., "p999"}``."""
        return {_q_label(q): self.quantile(q) for q in REPORT_QUANTILES}

    # -- merge ------------------------------------------------------------

    def _check_compatible(self, other: "QuantileDigest") -> None:
        if (self.eps, self.min_value) != (other.eps, other.min_value):
            raise ValueError(
                f"cannot merge digests with different boundaries: "
                f"(eps={self.eps}, min={self.min_value}) vs "
                f"(eps={other.eps}, min={other.min_value})"
            )

    def merge(self, other: "QuantileDigest") -> "QuantileDigest":
        """Fold ``other`` into self (bucket-wise addition; exact)."""
        self._check_compatible(other)
        self.zero += other.zero
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        return self

    # -- plain-data round trip --------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-able view (bucket keys stringified, sorted)."""
        return {
            "eps": self.eps,
            "min_value": self.min_value,
            "zero": self.zero,
            "buckets": {str(i): self.buckets[i]
                        for i in sorted(self.buckets)},
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
        }

    @classmethod
    def from_snapshot(cls, d: dict[str, Any]) -> "QuantileDigest":
        dig = cls(eps=float(d["eps"]), min_value=float(d["min_value"]))
        dig.zero = int(d["zero"])
        dig.buckets = {int(k): int(v) for k, v in d["buckets"].items()}
        dig.count = int(d["count"])
        dig.sum = float(d["sum"])
        if dig.count:
            dig.min = float(d["min"])
            dig.max = float(d["max"])
        return dig

    def reset(self) -> None:
        self.zero = 0
        self.buckets.clear()
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


def merge_digests(digests: Iterable[QuantileDigest],
                  eps: float = DEFAULT_EPS) -> QuantileDigest:
    """A fresh digest holding the bucket-wise sum of ``digests`` (the
    replica -> fleet rollup; an empty iterable merges to empty)."""
    out: QuantileDigest | None = None
    for d in digests:
        if out is None:
            out = QuantileDigest(eps=d.eps, min_value=d.min_value)
        out.merge(d)
    return out if out is not None else QuantileDigest(eps=eps)
