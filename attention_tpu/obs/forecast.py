"""Deterministic load forecasting over the frozen fleet series.

Holt double exponential smoothing (EWMA level + trend) with an
optional additive seasonal term (Holt-Winters) sized to the sim's
diurnal period, updated one observation per front-end tick::

    level_t = alpha * (x_t - season_t) + (1 - alpha) * (level + trend)
    trend_t = beta  * (level_t - level) + (1 - beta)  * trend
    season_t' = gamma * (x_t - level_t) + (1 - gamma) * season_t
    forecast(h) = level + h * trend + season_{t+h}

Every prediction is *backtested* as it is made: before folding in
observation ``x_t`` the forecaster records its own one-step-ahead
error, so the report carries a rolling MAPE and a residual-quantile
error band (``lo``/``hi`` widen with sqrt(h)) whose empirical coverage
is reported alongside.  All arithmetic is over virtual front-end ticks
(never wall time — ATP801-clean) and every container is emitted in
sorted order with a pinned ``generated_at``, so ``forecast_report`` is
byte-deterministic: same seed + same series -> same report, the
property ``cli obs forecast`` and the chaos ``forecast_determinism``
invariant pin.

This module is pure: it consumes plain per-tick sample lists (fed by
``ServingFrontend``'s ``ForecastTracker``) so it imports nothing above
the obs layer.  Registry mirrors land under the frozen names in
:mod:`attention_tpu.obs.naming` and only while telemetry is enabled.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Iterable

from attention_tpu.obs import registry as _registry
from attention_tpu.obs.naming import SERIES_FORECAST_PRESSURE

#: report format version (bumped on breaking shape changes)
FORECAST_REPORT_VERSION = 1

#: report-local name of the pressure sample series (the block the
#: capacity layer reads watermark crossings from)
PRESSURE_SERIES = "pressure"


def _r6(x: float) -> float:
    return round(float(x), 6)


@dataclasses.dataclass(frozen=True)
class ForecastPolicy:
    """Smoothing constants + horizon for one forecaster instance.

    ``season_ticks=None`` disables the seasonal term (plain Holt);
    set it to the workload's diurnal period to enable Holt-Winters.
    ``advisory`` gates the would-have-acted event hooks in the
    front end — it never changes routing or shedding decisions.
    """

    alpha: float = 0.5
    beta: float = 0.3
    gamma: float = 0.3
    season_ticks: int | None = None
    horizon: int = 8
    backtest_window: int = 64
    advisory: bool = False

    def validate(self) -> None:
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError("forecast alpha must be in (0, 1]")
        if not 0.0 <= self.beta <= 1.0:
            raise ValueError("forecast beta must be in [0, 1]")
        if not 0.0 <= self.gamma <= 1.0:
            raise ValueError("forecast gamma must be in [0, 1]")
        if self.season_ticks is not None and self.season_ticks < 2:
            raise ValueError("forecast season_ticks must be >= 2 ticks")
        if self.horizon < 1:
            raise ValueError("forecast horizon must be >= 1")
        if self.backtest_window < 2:
            raise ValueError("forecast backtest_window must be >= 2")

    def to_dict(self) -> dict[str, Any]:
        return {
            "alpha": self.alpha,
            "beta": self.beta,
            "gamma": self.gamma,
            "season_ticks": self.season_ticks,
            "horizon": self.horizon,
            "backtest_window": self.backtest_window,
            "advisory": self.advisory,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ForecastPolicy":
        p = cls(
            alpha=float(d["alpha"]),
            beta=float(d["beta"]),
            gamma=float(d["gamma"]),
            season_ticks=(None if d.get("season_ticks") is None
                          else int(d["season_ticks"])),
            horizon=int(d["horizon"]),
            backtest_window=int(d["backtest_window"]),
            advisory=bool(d.get("advisory", False)),
        )
        p.validate()
        return p


class HoltForecaster:
    """One Holt(-Winters) state machine, fed one sample per tick.

    Seasonal slots initialize to zero and are learned in place, so the
    first season's predictions lean on level+trend alone — deliberate:
    no warm-up pass means the update is strictly online and the state
    after n observations depends only on the n samples and the policy.
    """

    def __init__(self, policy: ForecastPolicy | None = None):
        self.policy = policy or ForecastPolicy()
        self.level = 0.0
        self.trend = 0.0
        self.seasonal: list[float] = [0.0] * (self.policy.season_ticks or 0)
        self.count = 0
        #: raw first-season buffer (seasonal mode only, dropped after
        #: the bootstrap re-initialization)
        self._warmup: list[float] = []
        #: one-step residuals (actual - predicted), rolling window
        self.residuals: list[float] = []
        self.actuals: list[float] = []

    def predict(self, h: int = 1) -> float:
        """Forecast ``h`` ticks past the last observation."""
        if self.count == 0:
            return 0.0
        out = self.level + h * self.trend
        if self.seasonal:
            out += self.seasonal[(self.count + h - 1) % len(self.seasonal)]
        return out

    def observe(self, x: float) -> None:
        x = float(x)
        if self.count:  # backtest before the state absorbs x
            self.residuals.append(x - self.predict(1))
            self.actuals.append(x)
            w = self.policy.backtest_window
            if len(self.residuals) > w:
                del self.residuals[:-w]
                del self.actuals[:-w]
        p = self.policy
        if self.count == 0:
            self.level = x
            if self.seasonal:
                self._warmup.append(x)
        elif self.seasonal and self.count < len(self.seasonal):
            # first season: plain Holt over the raw values while the
            # buffer fills (seasonal slots are all still zero)
            self._warmup.append(x)
            prev = self.level
            self.level = (p.alpha * x
                          + (1.0 - p.alpha) * (self.level + self.trend))
            self.trend = (p.beta * (self.level - prev)
                          + (1.0 - p.beta) * self.trend)
            if len(self._warmup) == len(self.seasonal):
                # classic HW bootstrap: level = first-season mean,
                # slots = deviations from it, trend restarted (a
                # drift estimate needs a second season; zero is the
                # deterministic safe prior)
                m = len(self.seasonal)
                self.level = sum(self._warmup) / m
                self.trend = 0.0
                self.seasonal = [v - self.level for v in self._warmup]
                self._warmup = []
        else:
            idx = self.count % len(self.seasonal) if self.seasonal else 0
            s = self.seasonal[idx] if self.seasonal else 0.0
            prev = self.level
            self.level = p.alpha * (x - s) + (1.0 - p.alpha) * (
                self.level + self.trend)
            self.trend = (p.beta * (self.level - prev)
                          + (1.0 - p.beta) * self.trend)
            if self.seasonal:
                self.seasonal[idx] = (
                    p.gamma * (x - self.level)
                    + (1.0 - p.gamma) * self.seasonal[idx])
        self.count += 1

    def backtest(self) -> dict[str, Any]:
        """Rolling one-step error stats over the residual window."""
        n = len(self.residuals)
        if not n:
            return {"points": 0, "one_step_mape": 0.0,
                    "band_p90": 0.0, "coverage": 0.0}
        # percentage error is undefined at actual ~ 0 (an idle series
        # would report astronomic MAPE for microscopic misses), so the
        # mean runs over the meaningfully-nonzero actuals only
        ape = [abs(r) / abs(a)
               for r, a in zip(self.residuals, self.actuals)
               if abs(a) >= 1e-6]
        ordered = sorted(abs(r) for r in self.residuals)
        band = ordered[min(n - 1, max(0, math.ceil(0.9 * n) - 1))]
        covered = sum(1 for r in self.residuals if abs(r) <= band)
        return {
            "points": n,
            "one_step_mape": _r6(sum(ape) / len(ape)) if ape else 0.0,
            "band_p90": _r6(band),
            "coverage": _r6(covered / n),
        }


def forecast_series(name: str, values: Iterable[float], *,
                    policy: ForecastPolicy | None = None,
                    horizon: int | None = None) -> dict[str, Any]:
    """One series block: final state, horizon table, backtest stats.

    ``forecast[i]["tick"]`` is the absolute virtual tick predicted
    (samples cover ticks ``0..n-1``, so ``h=1`` predicts tick ``n``).
    Error bands widen with sqrt(h) from the backtested one-step band.
    """
    p = policy or ForecastPolicy()
    h = int(p.horizon if horizon is None else horizon)
    fc = HoltForecaster(p)
    for v in values:
        fc.observe(v)
    bt = fc.backtest()
    table = []
    for step in range(1, h + 1):
        mean = fc.predict(step)
        band = bt["band_p90"] * math.sqrt(step)
        table.append({
            "h": step,
            "tick": fc.count + step - 1,
            "mean": _r6(mean),
            "lo": _r6(mean - band),
            "hi": _r6(mean + band),
        })
    return {
        "name": name,
        "ticks": fc.count,
        "state": {
            "level": _r6(fc.level),
            "trend": _r6(fc.trend),
            "seasonal": [_r6(s) for s in fc.seasonal],
        },
        "backtest": bt,
        "forecast": table,
    }


def forecast_report(series: dict[str, Iterable[float]], *,
                    policy: ForecastPolicy | None = None,
                    horizon: int | None = None) -> dict[str, Any]:
    """Deterministic forecast report over named per-tick sample series."""
    p = policy or ForecastPolicy()
    h = int(p.horizon if horizon is None else horizon)
    return {
        "version": FORECAST_REPORT_VERSION,
        "generated_at": 0,  # pinned: reports are seed-deterministic
        "horizon": h,
        "policy": p.to_dict(),
        "series": [forecast_series(name, series[name], policy=p, horizon=h)
                   for name in sorted(series)],
    }


def crossing(block: dict[str, Any], threshold: float) -> dict[str, Any] | None:
    """The first horizon row whose mean forecast reaches ``threshold``,
    or None if the series stays below it over the whole horizon."""
    for row in block["forecast"]:
        if row["mean"] >= threshold:
            return row
    return None


def publish(report: dict[str, Any]) -> None:
    """Mirror the pressure forecast onto the frozen registry series
    (no-op while telemetry is disabled)."""
    if not _registry.is_enabled():
        return
    g = _registry.gauge(SERIES_FORECAST_PRESSURE,
                        "forecast mean fleet pressure by horizon")
    for blk in report["series"]:
        if blk["name"] != PRESSURE_SERIES:
            continue
        for row in blk["forecast"]:
            g.set(row["mean"], horizon=str(row["h"]))
