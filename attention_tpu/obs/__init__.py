"""Unified telemetry: typed instruments, host spans, merged timelines.

The observability layer SURVEY §5 planned and the serving engine needs:
the reference's entire story was a printf of wall time
(`attention.c:186-188`); ours is three composable pieces sharing one
process-wide state:

* **Registry** (`obs.registry`) — counters / gauges / fixed-bucket
  histograms with labeled series, ``snapshot()``/``reset()``;
* **Spans** (`obs.spans`) — ``with obs.span("engine.step"):`` records a
  host start/duration event into a bounded ring AND enters
  ``profiling.annotate`` so the same name lands in HLO;
* **Exporters** (`obs.export`) — Prometheus text (:func:`prom_text`),
  JSONL, and a Chrome-trace timeline merging host spans with the XLA
  device lane (``cli obs export --format chrome|prom|jsonl``).

Telemetry is **disabled by default** and the disabled path is a single
flag check (no allocation, no clock read — asserted by test).  Enable
with :func:`enable` or ``ATTN_TPU_OBS=1``.  Instrument handles may be
created at import time regardless of the flag::

    from attention_tpu import obs

    _CALLS = obs.counter("ops.flash.calls")

    def f(q, ...):
        _CALLS.inc(bucket=obs.shape_bucket(q.shape))
        with obs.span("engine.step"):
            ...

Names follow ``layer.component.verb`` (`obs.naming`, linted tree-wide
by ``scripts/check_obs_names.py``).
"""

from __future__ import annotations

from typing import Any

from attention_tpu.obs.export import (  # noqa: F401
    chrome_trace,
    device_dir_of,
    dump,
    jsonl_lines,
    load_anomaly,
    load_blackbox,
    load_dump,
    load_forecast,
    load_slo,
    load_traces,
    prom_text,
    write_anomaly,
    write_forecast,
    write_jsonl,
    write_slo,
)
from attention_tpu.obs.naming import (  # noqa: F401
    ANOMALY_DETECTORS,
    BLACKBOX_EVENTS,
    FROZEN_SERIES,
    TRACE_EVENTS,
    TRACE_TERMINAL_EVENTS,
    check_blackbox_event,
    check_event,
    check_name,
    require_blackbox_event,
    require_event,
    require_name,
)
from attention_tpu.obs.quantile import (  # noqa: F401
    QuantileDigest,
    merge_digests,
)
from attention_tpu.obs.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Digest,
    Gauge,
    Histogram,
    Registry,
    counter,
    digest,
    disable,
    enable,
    gauge,
    histogram,
    is_enabled,
)
from attention_tpu.obs.spans import (  # noqa: F401
    SPAN_RING_CAPACITY,
    events,
    record_event,
    span,
)
from attention_tpu.obs import anomaly  # noqa: F401
from attention_tpu.obs import blackbox  # noqa: F401
from attention_tpu.obs import capacity  # noqa: F401
from attention_tpu.obs import forecast  # noqa: F401
from attention_tpu.obs import postmortem  # noqa: F401
from attention_tpu.obs import slo  # noqa: F401
from attention_tpu.obs import spans as _spans
from attention_tpu.obs import trace  # noqa: F401


def enabled() -> bool:
    """Alias of :func:`is_enabled` (reads better at call sites)."""
    return is_enabled()


def reset() -> None:
    """Zero every metric series and drop every span event, request
    trace, and flight-recorder record (instrument registrations
    survive)."""
    REGISTRY.reset()
    _spans.clear()
    trace.clear()
    blackbox.clear()


def shape_bucket(*dims: int) -> str:
    """Power-of-two shape-bucket label, e.g. ``shape_bucket(3000, 128)
    -> "4096x128"`` — the tuning cache's bucketing discipline reused as
    a low-cardinality metric label."""
    out = []
    for d in dims:
        d = int(d)
        b = 1
        while b < d:
            b <<= 1
        out.append(str(b))
    return "x".join(out)


_RUNS = counter("bench.runs.recorded",
                "RunRecords re-emitted through the registry")
_RUN_US = gauge("bench.run.best_us", "best-run µs by config/backend")
_RUN_UTIL = gauge("bench.run.utilization",
                  "fraction-of-peak by config/backend")


def record_run(record: Any) -> None:
    """Re-emit a `utils.profiling.RunRecord` (or its dict) through the
    registry, so benchmark rows and engine summaries land in the same
    scrape as live counters."""
    if not is_enabled():
        return
    import dataclasses

    d = (dataclasses.asdict(record)
         if dataclasses.is_dataclass(record) else dict(record))
    labels = {"config": str(d.get("config", "")),
              "backend": str(d.get("backend", ""))}
    _RUNS.inc(**labels)
    _RUN_US.set(float(d.get("best_us", 0.0)), **labels)
    _RUN_UTIL.set(float(d.get("utilization", 0.0)), **labels)
