"""SLO objectives + error-budget burn-rate accounting.

Objectives are declared over the two serving latencies the scheduling
literature (Orca, Sarathi-Serve) treats as primary — TTFT (ticks from
submit to first token) and TPOT (ticks per output token after the
first) — at a target quantile per tenant and priority class.  All
arithmetic is over front-end ticks (never wall time) and every
container is emitted in sorted order with a pinned ``generated_at``,
so ``slo_report`` is byte-deterministic: same seed, same report — the
property ``cli obs slo`` pins.

Error-budget semantics: an objective "p99 <= N ticks" allows 1% of
requests to miss N.  ``burn_rate`` is (observed miss fraction) /
(allowed miss fraction) — 1.0 means spending budget exactly at the
allowed rate, >1 means burning it — reported both over the whole run
and as a rolling per-window series (the forecaster input surface; the
series names live in :mod:`attention_tpu.obs.naming` and are frozen).

This module is pure: it consumes plain latency *rows* (produced by
``ServingFrontend.latency_rows`` / ``EngineMetrics``) so it imports
nothing above the obs layer.

Row schema (one dict per terminal request)::

    {"request_id": str, "tenant": str, "priority": int,
     "submit_tick": int, "first_token_tick": int | None,
     "finish_tick": int, "output_tokens": int, "state": str}
"""

from __future__ import annotations

import dataclasses
from typing import Any

from attention_tpu.obs import registry as _registry
from attention_tpu.obs.naming import (
    SERIES_SLO_BUDGET,
    SERIES_SLO_BURN_RATE,
    SERIES_SLO_VIOLATIONS,
)
from attention_tpu.obs.quantile import QuantileDigest

#: report format version (bumped on breaking shape changes)
SLO_REPORT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One latency objective: ``metric`` at ``quantile`` must stay
    <= ``threshold_ticks``, accounted over rolling ``window_ticks``."""

    name: str
    metric: str  # "ttft" | "tpot"
    quantile: float
    threshold_ticks: float
    window_ticks: int

    def __post_init__(self):
        if self.metric not in ("ttft", "tpot"):
            raise ValueError(
                f"objective {self.name}: metric must be 'ttft' or "
                f"'tpot', got {self.metric!r}"
            )
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(
                f"objective {self.name}: quantile must be in (0, 1)"
            )
        if self.window_ticks < 1:
            raise ValueError(
                f"objective {self.name}: window_ticks must be >= 1"
            )

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "metric": self.metric,
            "quantile": self.quantile,
            "threshold_ticks": self.threshold_ticks,
            "window_ticks": self.window_ticks,
        }


#: default objectives for the simulated fleet (tick-denominated)
DEFAULT_OBJECTIVES = (
    SLObjective("ttft_p99", "ttft", 0.99, 48.0, 64),
    SLObjective("tpot_p99", "tpot", 0.99, 4.0, 64),
)


def _r6(x: float) -> float:
    return round(float(x), 6)


def _metric_value(row: dict[str, Any], metric: str) -> float | None:
    """The row's value for ``metric``, or None when undefined (never
    reached first token / fewer than two output tokens)."""
    ft = row.get("first_token_tick")
    if metric == "ttft":
        if ft is None:
            return None
        return float(ft - row["submit_tick"])
    if ft is None or row.get("output_tokens", 0) < 2:
        return None
    return float(row["finish_tick"] - ft) / (row["output_tokens"] - 1)


def _objective_block(rows: list[dict[str, Any]], obj: SLObjective,
                     horizon_tick: int) -> dict[str, Any]:
    """Accounting for one objective over one group's rows."""
    allowed = 1.0 - obj.quantile
    dig = QuantileDigest()
    # (finish_tick, violated) per accountable request: a request that
    # died before its metric was ever defined (shed, timed out before
    # first token) burns TTFT budget — the user saw no token — but is
    # not accountable for TPOT (there was nothing to time)
    marks: list[tuple[int, bool]] = []
    for row in rows:
        v = _metric_value(row, obj.metric)
        if v is None:
            if obj.metric == "ttft":
                marks.append((row["finish_tick"], True))
            continue
        dig.add(v)
        marks.append((row["finish_tick"], v > obj.threshold_ticks))
    count = len(marks)
    violations = sum(1 for _, bad in marks if bad)
    frac = violations / count if count else 0.0
    burn = frac / allowed if count else 0.0
    budget = 1.0 - burn
    w = obj.window_ticks
    series = []
    end = w
    while end < horizon_tick + w:
        in_w = [bad for t, bad in marks if end - w < t <= end]
        wf = (sum(in_w) / len(in_w)) if in_w else 0.0
        series.append({
            "window_end": end,
            "requests": len(in_w),
            "burn_rate": _r6(wf / allowed),
        })
        end += w
    return {
        "objective": obj.name,
        "metric": obj.metric,
        "threshold_ticks": obj.threshold_ticks,
        "achieved": _r6(dig.quantile(obj.quantile)),
        "requests": count,
        "violations": violations,
        "allowed_fraction": _r6(allowed),
        "burn_rate": _r6(burn),
        "budget_remaining": _r6(budget),
        "burn_series": series,
    }


def _latency_block(rows: list[dict[str, Any]], metric: str) -> dict[str, Any]:
    dig = QuantileDigest()
    for row in rows:
        v = _metric_value(row, metric)
        if v is not None:
            dig.add(v)
    out = {k: _r6(v) for k, v in dig.percentiles().items()}
    out["count"] = dig.count
    return out


def slo_report(rows: list[dict[str, Any]],
               objectives: tuple[SLObjective, ...] = DEFAULT_OBJECTIVES,
               *, horizon_tick: int) -> dict[str, Any]:
    """Deterministic SLO report over terminal-request latency rows.

    Groups by (tenant, priority); the ``fleet`` block re-runs the same
    accounting over all rows at once (== merging the group digests:
    bucket-wise addition is exact)."""
    groups: dict[tuple[str, int], list[dict[str, Any]]] = {}
    for row in rows:
        key = (str(row.get("tenant") or "default"),
               int(row.get("priority", 0)))
        groups.setdefault(key, []).append(row)

    def block(sub: list[dict[str, Any]]) -> dict[str, Any]:
        return {
            "requests": len(sub),
            "ttft": _latency_block(sub, "ttft"),
            "tpot": _latency_block(sub, "tpot"),
            "slo": [_objective_block(sub, o, horizon_tick)
                    for o in objectives],
        }

    return {
        "version": SLO_REPORT_VERSION,
        "generated_at": 0,  # pinned: reports are seed-deterministic
        "horizon_tick": int(horizon_tick),
        "objectives": [o.to_dict() for o in objectives],
        "groups": [
            {"tenant": t, "priority": p, **block(groups[(t, p)])}
            for t, p in sorted(groups)
        ],
        "fleet": block(rows),
    }


def publish(report: dict[str, Any]) -> None:
    """Mirror a report's headline numbers onto the frozen registry
    series (no-op while telemetry is disabled)."""
    if not _registry.is_enabled():
        return
    burn = _registry.gauge(SERIES_SLO_BURN_RATE,
                           "SLO error-budget burn rate")
    budget = _registry.gauge(SERIES_SLO_BUDGET,
                             "SLO error budget remaining")
    viols = _registry.counter(SERIES_SLO_VIOLATIONS,
                              "SLO violations")
    for grp in report["groups"]:
        labels = {"tenant": grp["tenant"],
                  "priority": str(grp["priority"])}
        for ob in grp["slo"]:
            lb = {"objective": ob["objective"], **labels}
            burn.set(ob["burn_rate"], **lb)
            budget.set(ob["budget_remaining"], **lb)
            if ob["violations"]:
                viols.inc(ob["violations"], **lb)
