"""Per-request distributed tracing: append-only event chains.

A **RequestTrace** is the journey of one request through the fleet: an
append-only chain of typed events (closed enum in
:mod:`attention_tpu.obs.naming`) each stamped with the four
deterministic coordinates of the serving stack —

    ``(front-end tick, replica id, incarnation, engine step)``

— never wall time, so the same seed produces byte-identical chains.
The chain survives every fleet transition: migration carries the tail
inside the drained request record, warm restart rides the per-request
snapshot section (``snapshot._request_to_dict`` embeds the tail,
``adopt`` splices it back, deduplicating against whatever the live
store already saw), and retry-with-backoff appends ``retried`` hops to
the same chain.  ``obs.dump`` persists every chain to ``traces.jsonl``
so a journey through a kill+gray storm reconstructs from the dump
alone (``cli obs trace --request ID``).

Gating: recording is off unless telemetry is enabled (the PR 3
zero-overhead contract — the disabled path is one global read and a
return) or a :func:`capture` scope is active.  ``capture`` exists for
the chaos harness: fault campaigns assert trace completeness without
turning the whole registry on.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator

from attention_tpu.obs import registry as _registry
from attention_tpu.obs.naming import TRACE_TERMINAL_EVENTS, require_event

#: most chains kept live; oldest request's chain drops first
TRACE_CAPACITY = 65536

_lock = threading.Lock()
_traces: dict[str, list[dict[str, Any]]] = {}
_forced = 0  # >0 inside a capture() scope: record regardless of obs flag


def active() -> bool:
    """True iff trace recording is currently on."""
    return _registry._enabled or _forced > 0


@contextlib.contextmanager
def capture() -> Iterator[None]:
    """Scope that records traces even while telemetry is disabled.

    Clears the store on entry — each chaos plan gets an isolated set of
    chains to assert completeness over (synthetic request ids repeat
    across plans)."""
    global _forced
    with _lock:
        _forced += 1
        _traces.clear()
    try:
        yield
    finally:
        with _lock:
            _forced -= 1


def record(request_id: str, event: str, *, tick: int,
           replica: str | None = None, incarnation: int = 0,
           step: int = -1, **extra: Any) -> None:
    """Append one event to ``request_id``'s chain.

    ``extra`` carries hop details (``source``/``dest`` for migrations,
    ``attempt``/``delay`` for retries) and must be plain scalars — the
    chain is serialized verbatim into snapshots and dumps."""
    if not (_registry._enabled or _forced):
        return
    require_event(event)
    ev: dict[str, Any] = {
        "event": event,
        "tick": int(tick),
        "replica": replica,
        "incarnation": int(incarnation),
        "step": int(step),
    }
    for k in sorted(extra):
        v = extra[k]
        if v is not None and not isinstance(v, (str, int, float, bool)):
            raise TypeError(
                f"trace extra {k}={v!r} must be a plain scalar"
            )
        ev[k] = v
    with _lock:
        chain = _traces.get(request_id)
        if chain is None:
            if len(_traces) >= TRACE_CAPACITY:
                _traces.pop(next(iter(_traces)))
            chain = _traces[request_id] = []
        chain.append(ev)


def _ev_key(ev: dict[str, Any]) -> tuple:
    return tuple(sorted(ev.items()))


def adopt(request_id: str, events: list[dict[str, Any]]) -> None:
    """Splice a restored chain tail (from a snapshot or a migration
    record) into the live store, skipping events already present —
    idempotent, so in-process warm restarts (store survived) and
    fresh-process restores (store empty) both end with one copy."""
    if not (_registry._enabled or _forced):
        return
    if not events:
        return
    with _lock:
        chain = _traces.get(request_id)
        if chain is None:
            if len(_traces) >= TRACE_CAPACITY:
                _traces.pop(next(iter(_traces)))
            _traces[request_id] = [dict(ev) for ev in events]
            return
        seen = {_ev_key(ev) for ev in chain}
        for ev in events:
            if _ev_key(ev) not in seen:
                chain.append(dict(ev))


def events_of(request_id: str) -> list[dict[str, Any]]:
    """The chain for one request, oldest first (copy; [] if unknown)."""
    with _lock:
        return [dict(ev) for ev in _traces.get(request_id, ())]


def all_traces() -> dict[str, list[dict[str, Any]]]:
    """Every live chain, keyed by request id (copies)."""
    with _lock:
        return {rid: [dict(ev) for ev in chain]
                for rid, chain in _traces.items()}


def terminal_of(events: list[dict[str, Any]]) -> str | None:
    """The terminal event name of a chain, or None if still open."""
    for ev in reversed(events):
        if ev["event"] in TRACE_TERMINAL_EVENTS:
            return ev["event"]
    return None


def journey_lines(request_id: str,
                  events: list[dict[str, Any]]) -> list[str]:
    """Human-readable journey report for one chain (the ``cli obs
    trace --request ID`` body)."""
    term = terminal_of(events)
    lines = [
        f"request {request_id}: {len(events)} events, "
        f"terminal={term or 'none (in flight)'}"
    ]
    for ev in events:
        where = ""
        if ev.get("replica") is not None:
            where = f" replica={ev['replica']} inc={ev['incarnation']}"
            if ev.get("step", -1) >= 0:
                where += f" step={ev['step']}"
        extras = [
            f"{k}={ev[k]}" for k in sorted(ev)
            if k not in ("event", "tick", "replica", "incarnation", "step")
            and ev[k] is not None
        ]
        tail = (" [" + " ".join(extras) + "]") if extras else ""
        lines.append(
            f"  [tick {ev['tick']:>4}] {ev['event']}{where}{tail}"
        )
    return lines


def clear() -> None:
    with _lock:
        _traces.clear()
