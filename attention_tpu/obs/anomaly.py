"""Deterministic online anomaly detectors over the frozen series.

Three advisory detectors run inside the frontend tick loop, every one
a pure function of the same frozen byte-deterministic inputs the
forecaster stack consumes (`obs/naming.py:FROZEN_SERIES` — never wall
time, never the registry itself at decision time):

* **residual_band** — the one-step forecaster residual of mean fleet
  pressure leaves its backtested p90 band (the
  :class:`~attention_tpu.obs.forecast.HoltForecaster` residual state,
  re-used as the detector's own model);
* **burn_slope** — an SLO objective's error-budget burn rate RISES
  across two adjacent windows (absolute burn is the SLO report's job;
  the slope is the early-warning signal);
* **gray_failure** — one replica's recent inter-token gaps (its
  per-replica TTFT/TPOT view) diverge from the merge of its peers
  beyond a pinned ratio — the partially-failed-but-not-dead replica
  the supervisor's liveness checks cannot see.

Like :class:`~attention_tpu.obs.forecast.ForecastTracker`, the tracker
is plain Python state fed by the frontend regardless of the telemetry
flag — detection works with the registry off, and the off↔on token
streams stay byte-identical because detectors are advisory-only: a
firing is recorded (tracker state, blackbox ring, incident bundle),
never acted on.  Gauges under the frozen ``frontend.anomaly.*`` names
publish only when telemetry is enabled.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any

from attention_tpu.obs import registry as _registry
from attention_tpu.obs.forecast import ForecastPolicy, HoltForecaster
from attention_tpu.obs.naming import (
    SERIES_ANOMALY_BURN_SLOPE,
    SERIES_ANOMALY_FIRINGS,
    SERIES_ANOMALY_GRAY_SCORE,
    SERIES_ANOMALY_RESIDUAL,
    require_detector,
)
from attention_tpu.obs.registry import counter, gauge
from attention_tpu.obs.slo import DEFAULT_OBJECTIVES

ANOMALY_REPORT_VERSION = 1

#: inter-token gaps are clipped here — a single pathological stall
#: must not poison a replica's window mean forever
GRAY_GAP_CLIP = 16.0



def _r6(x: float) -> float:
    return round(float(x), 6)


def _p90(xs: list[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(int(0.9 * len(s)), len(s) - 1)]


@dataclasses.dataclass(frozen=True)
class AnomalyPolicy:
    """Pinned detector bounds (all advisory; validated at frontend
    construction like :class:`~attention_tpu.obs.forecast.ForecastPolicy`)."""

    #: residual_band: |residual| must exceed band_p90 * scale ...
    residual_scale: float = 3.0
    #: ... and this floor (cold bands are tiny; don't fire on noise)
    residual_min_band: float = 0.5
    #: residual_band: ticks of forecaster history before arming
    residual_warmup: int = 12
    #: burn_slope: window width in ticks (two adjacent windows compared)
    burn_window: int = 32
    #: burn_slope: fire when recent burn - prior burn exceeds this
    burn_slope_bound: float = 2.0
    #: burn_slope: min finished requests per window before arming
    burn_min_requests: int = 4
    #: gray_failure: samples older than this many ticks are ignored
    gray_window: int = 64
    #: gray_failure: replica trail mean / peer mean ratio that fires
    gray_ratio: float = 2.0
    #: gray_failure: min recent samples on BOTH sides before arming
    gray_min_samples: int = 4
    #: gray_failure: per-replica recent-sample trail length (recency
    #: beats a tick-window mean: a degraded replica's first slow
    #: tokens move the score immediately instead of drowning in
    #: pre-fault samples)
    gray_trail: int = 8

    def validate(self) -> None:
        if self.residual_scale <= 0 or self.residual_min_band < 0:
            raise ValueError(
                "residual_scale must be > 0 and residual_min_band >= 0")
        if self.residual_warmup < 1:
            raise ValueError("residual_warmup must be >= 1")
        if self.burn_window < 2 or self.burn_min_requests < 1:
            raise ValueError(
                "burn_window must be >= 2 and burn_min_requests >= 1")
        if self.burn_slope_bound <= 0:
            raise ValueError("burn_slope_bound must be > 0")
        if self.gray_window < 1 or self.gray_min_samples < 1:
            raise ValueError(
                "gray_window and gray_min_samples must be >= 1")
        if self.gray_trail < 1:
            raise ValueError("gray_trail must be >= 1")
        if self.gray_ratio <= 1.0:
            raise ValueError("gray_ratio must be > 1.0")

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "AnomalyPolicy":
        return cls(**d)


# the frozen gauges the detectors publish onto (creation is allowed
# while disabled; recording is gated inside the registry)
_RESIDUAL_G = gauge(SERIES_ANOMALY_RESIDUAL,
                    "one-step forecast residual of mean fleet pressure")
_BURN_SLOPE_G = gauge(SERIES_ANOMALY_BURN_SLOPE,
                      "SLO burn-rate slope across adjacent windows")
_GRAY_G = gauge(SERIES_ANOMALY_GRAY_SCORE,
                "per-replica latency divergence vs peer merge")
_FIRINGS_C = counter(SERIES_ANOMALY_FIRINGS,
                     "anomaly detector firings by detector")


class AnomalyTracker:
    """Online detector state, fed from the frontend tick loop.

    Feeds (all plain scalars, all deterministic):

    * :meth:`observe_pressure` — per-tick mean fleet pressure
      (residual_band input);
    * :meth:`observe_latency` — per-finished-request TTFT/TPOT in
      ticks (burn_slope input, same row math as `obs.slo`);
    * :meth:`observe_tokens` — per-tick token emissions per request
      (gray_failure input: inter-token gaps per replica).

    :meth:`step` runs the detectors once per tick and returns the NEW
    firings (rising-edge: a condition that stays true keeps one firing
    active rather than firing every tick — incident bundles stay
    bounded)."""

    def __init__(self, policy: AnomalyPolicy | None = None):
        self.policy = policy or AnomalyPolicy()
        self.policy.validate()
        # residual_band
        self._fc = HoltForecaster(ForecastPolicy())
        self._residual = 0.0
        self._band = 0.0
        # burn_slope: objective name -> deque[(tick, violated)]
        self._burn: dict[str, collections.deque] = {
            o.name: collections.deque(maxlen=4096)
            for o in DEFAULT_OBJECTIVES
        }
        self._objectives = {o.name: o for o in DEFAULT_OBJECTIVES}
        self._slopes: dict[str, float] = {}
        # gray_failure: replica -> deque[(tick, gap_per_token)]
        self._gaps: dict[str, collections.deque] = {}
        self._last_emit: dict[str, tuple[str, int]] = {}
        self._scores: dict[str, float] = {}
        #: (detector, key) pairs whose condition currently holds
        self.active: set[tuple[str, str]] = set()
        #: every rising-edge firing, in firing order
        self.firings: list[dict[str, Any]] = []

    # -- feeds -------------------------------------------------------------

    def observe_pressure(self, tick: int, mean_pressure: float) -> None:
        """One fleet-pressure sample; backtests the residual BEFORE
        the forecaster absorbs it (the `HoltForecaster.observe`
        discipline)."""
        del tick
        if self._fc.count >= 1:
            self._residual = float(mean_pressure) - self._fc.predict(1)
        self._fc.observe(float(mean_pressure))
        self._band = _p90([abs(r) for r in self._fc.residuals])

    def observe_latency(self, tick: int, ttft_ticks: float | None,
                        tpot_ticks: float | None) -> None:
        """One finished request's latency row (ticks, never wall
        time); None marks the metric unavailable (counts as a TTFT
        violation, skipped for TPOT — the `obs.slo` row rules)."""
        for name, obj in self._objectives.items():
            if obj.metric == "ttft":
                v = 1 if (ttft_ticks is None
                          or ttft_ticks > obj.threshold_ticks) else 0
            else:
                if tpot_ticks is None:
                    continue
                v = 1 if tpot_ticks > obj.threshold_ticks else 0
            self._burn[name].append((int(tick), v))

    def observe_tokens(self, tick: int, replica: str, request_id: str,
                       n_tokens: int) -> None:
        """``n_tokens`` new output tokens for ``request_id`` on
        ``replica`` at ``tick``; consecutive emissions yield
        inter-token gap samples (the first emission only arms the
        clock)."""
        if n_tokens <= 0:
            return
        prev = self._last_emit.get(request_id)
        if prev is not None:
            prev_replica, prev_tick = prev
            # a cross-replica gap measures the migration (retry,
            # adoption), not the destination replica — re-arm only,
            # else a sick replica's evacuees get its peers flagged
            if prev_replica == replica:
                gap = min((tick - prev_tick) / float(n_tokens),
                          GRAY_GAP_CLIP)
                q = self._gaps.get(replica)
                if q is None:
                    q = self._gaps[replica] = collections.deque(
                        maxlen=512)
                q.append((int(tick), gap))
        self._last_emit[request_id] = (replica, int(tick))

    def forget_request(self, request_id: str) -> None:
        """Drop the per-request emission clock (terminal request)."""
        self._last_emit.pop(request_id, None)

    # -- detectors ---------------------------------------------------------

    def _burn_rate(self, name: str, lo: int, hi: int) -> tuple[float, int]:
        """(burn rate, request count) over window ticks (lo, hi]."""
        obj = self._objectives[name]
        n = viol = 0
        for t, v in self._burn[name]:
            if lo < t <= hi:
                n += 1
                viol += v
        if n == 0:
            return 0.0, 0
        return (viol / n) / (1.0 - obj.quantile), n

    def _edge(self, tick: int, detector: str, key: str, cond: bool,
              value: float, bound: float,
              new: list[dict[str, Any]]) -> None:
        """Rising-edge bookkeeping shared by all three detectors."""
        require_detector(detector)
        state = (detector, key)
        if cond and state not in self.active:
            self.active.add(state)
            firing = {"tick": int(tick), "detector": detector,
                      "key": key, "value": _r6(value),
                      "bound": _r6(bound)}
            self.firings.append(firing)
            new.append(firing)
        elif not cond:
            self.active.discard(state)

    def step(self, tick: int) -> list[dict[str, Any]]:
        """Run every detector once; returns the NEW firings at this
        tick (possibly empty)."""
        p = self.policy
        new: list[dict[str, Any]] = []

        # residual_band
        bound = max(self._band * p.residual_scale, p.residual_min_band)
        armed = self._fc.count >= p.residual_warmup
        self._edge(tick, "residual_band", "fleet",
                   armed and abs(self._residual) > bound,
                   abs(self._residual), bound, new)

        # burn_slope
        for name in sorted(self._burn):
            recent, n_r = self._burn_rate(
                name, tick - p.burn_window, tick)
            prior, n_p = self._burn_rate(
                name, tick - 2 * p.burn_window, tick - p.burn_window)
            slope = recent - prior
            self._slopes[name] = slope
            armed = (n_r >= p.burn_min_requests
                     and n_p >= p.burn_min_requests)
            self._edge(tick, "burn_slope", name,
                       armed and slope > p.burn_slope_bound,
                       slope, p.burn_slope_bound, new)

        # gray_failure
        means: dict[str, tuple[float, int]] = {}
        for rep in sorted(self._gaps):
            recent = [g for t, g in self._gaps[rep]
                      if t > tick - p.gray_window]
            trail = recent[-p.gray_trail:]
            if trail:
                means[rep] = (sum(trail) / len(trail), len(trail))
        for rep in sorted(means):
            mine, n_mine = means[rep]
            peer_sum = peer_n = 0.0
            for other, (m, n) in means.items():
                if other != rep:
                    peer_sum += m * n
                    peer_n += n
            if peer_n >= p.gray_min_samples and peer_sum > 0:
                score = mine / (peer_sum / peer_n)
            else:
                score = 1.0
            self._scores[rep] = score
            armed = (n_mine >= p.gray_min_samples
                     and peer_n >= p.gray_min_samples)
            self._edge(tick, "gray_failure", rep,
                       armed and score > p.gray_ratio,
                       score, p.gray_ratio, new)
        return new

    # -- outputs -----------------------------------------------------------

    def publish(self, new_firings: list[dict[str, Any]]) -> None:
        """Mirror detector state onto the frozen gauges (no-op while
        telemetry is disabled — the registry gates every set)."""
        if not _registry.is_enabled():
            return
        _RESIDUAL_G.set(_r6(self._residual))
        for name in sorted(self._slopes):
            _BURN_SLOPE_G.set(_r6(self._slopes[name]), objective=name)
        for rep in sorted(self._scores):
            _GRAY_G.set(_r6(self._scores[rep]), replica=rep)
        for f in new_firings:
            _FIRINGS_C.inc(detector=f["detector"])

    def report(self) -> dict[str, Any]:
        """Canonical plain-data detector state (the ``anomaly.json``
        dump and the ``cli obs report`` anomalies section)."""
        return {
            "version": ANOMALY_REPORT_VERSION,
            "generated_at": 0,
            "policy": self.policy.to_dict(),
            "detectors": {
                "residual_band": {
                    "residual": _r6(self._residual),
                    "band_p90": _r6(self._band),
                    "observed_ticks": self._fc.count,
                },
                "burn_slope": {
                    name: _r6(self._slopes.get(name, 0.0))
                    for name in sorted(self._burn)
                },
                "gray_failure": {
                    rep: _r6(self._scores[rep])
                    for rep in sorted(self._scores)
                },
            },
            "active": sorted(
                [list(pair) for pair in self.active]),
            "firings": [dict(f) for f in self.firings],
        }
